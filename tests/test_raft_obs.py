"""Consensus-plane observatory tests (obs/raftstats.py).

Unit coverage for the latency histograms (bucket placement, cumulative
rendering, no-wrap banks per the PR 5 HistRecorder convention), the
bounded event timeline ring, and the anti-entropy stats — plus
compressed-timer cluster tests holding the live instrumentation to the
leader / follower / deposed-leader contracts and the Prometheus
exposition to tools/check_prom.
"""

from __future__ import annotations

import asyncio

import pytest

from consul_tpu.agent.local import LocalState
from consul_tpu.obs import raftstats
from consul_tpu.obs.prom import render_prometheus
from consul_tpu.obs.raftstats import (
    MS_EDGES, TIMELINE_CAP, AntiEntropyStats, LatencyHist, RaftStats)
from tests.test_raft import (
    make_cluster, put, start_all, stop_all, wait_for_leader, wait_until)
from tools.check_prom import _iter_series, _require_ok, check_text


# -- LatencyHist ------------------------------------------------------------


def test_hist_bucket_placement_and_family():
    h = LatencyHist("consul_raft_test_ms", "test")
    h.observe(0.1)      # below first edge -> first bucket
    h.observe(3.0)      # -> le=2.5 is too small; lands in le=5
    h.observe(10.0)     # exact edge is inclusive (le semantics)
    h.observe(9999.0)   # beyond last edge -> +Inf only
    fam = h.family()
    assert fam["name"] == "consul_raft_test_ms"
    assert fam["count"] == 4
    assert fam["sum"] == pytest.approx(0.1 + 3.0 + 10.0 + 9999.0)
    by_le = dict(fam["buckets"])
    assert by_le["0.25"] == 1
    assert by_le["2.5"] == 1          # cumulative: only the 0.1 obs
    assert by_le["5"] == 2
    assert by_le["10"] == 3
    assert by_le[str(int(MS_EDGES[-1]))] == 3  # 9999 only in +Inf
    # buckets are cumulative and monotonic
    counts = [c for _, c in fam["buckets"]]
    assert counts == sorted(counts)


def test_hist_no_wrap_past_2_32():
    """The PR 5 convention: host banks are unbounded ints — a bucket
    holding more than 2**32 observations must stay exact, not wrap."""
    h = LatencyHist("consul_raft_test_ms", "test")
    big = 2 ** 32 + 5
    h.observe(1.0, n=big)
    h.observe(1.0)
    assert h.count == big + 1
    fam = h.family()
    assert dict(fam["buckets"])["1"] == big + 1
    assert fam["count"] == big + 1


def test_hist_quantiles():
    h = LatencyHist("consul_raft_test_ms", "test")
    assert h.quantile_ms(0.5) is None
    for _ in range(99):
        h.observe(0.6)   # -> le=1 bucket
    h.observe(2000.0)    # -> le=2500 bucket
    assert h.quantile_ms(0.5) == 1.0
    assert h.quantile_ms(0.99) == 1.0
    assert h.wire()["p50_ms"] == 1.0


# -- timeline ring ----------------------------------------------------------


def test_timeline_ring_bounded_and_ordered():
    rs = RaftStats("n1")
    for i in range(TIMELINE_CAP + 40):
        rs.event("election-start", term=i)
    tl = rs.timeline()
    assert len(tl) == TIMELINE_CAP
    assert rs.events_total == TIMELINE_CAP + 40
    terms = [ev["term"] for ev in tl]
    # oldest retained first, newest last, contiguous
    assert terms == list(range(40, TIMELINE_CAP + 40))


def test_lease_observe_transitions():
    rs = RaftStats("n1")
    rs.lease_observe(12.0, term=3)    # invalid -> valid
    rs.lease_observe(8.0, term=3)     # still valid: no new event
    rs.lease_observe(0.0, term=3)     # valid -> invalid
    kinds = [ev["kind"] for ev in rs.timeline()]
    assert kinds == ["lease-acquired", "lease-lost"]
    assert rs.lease_margin.count == 2  # only valid samples observed


# -- pending-stamp pipeline -------------------------------------------------


def test_append_commit_apply_pipeline():
    rs = RaftStats("n1")
    rs.note_append(5)
    rs.note_append(7)
    rs.note_commit(5)            # pops index 5 only
    assert rs.append_quorum.count == 1
    rs.note_commit(7)
    assert rs.append_quorum.count == 2
    rs.note_applied(6)           # drains the commit stamp for 5 only
    assert rs.commit_apply.count == 1
    rs.note_applied(7)
    assert rs.commit_apply.count == 2


def test_peer_fail_recover_counters():
    rs = RaftStats("n1")
    rs.peer_fail("s2")
    rs.peer_fail("s2")
    rs.peer_ok("s2", sent=1.0)
    rs.peer_ok("s2", sent=2.0)

    class FakeNode:
        match_index = {"s2": 3}

        def last_log_index(self):
            return 10

    rows = rs.peer_rows(FakeNode())
    assert len(rows) == 1
    row = rows[0]
    assert row["peer"] == "s2"
    assert row["rpc_failed"] == 2
    assert row["rpc_recovered"] == 1   # one failure episode ended
    assert row["match_lag_entries"] == 7
    assert row["last_contact_age_ms"] is not None


# -- live clusters ----------------------------------------------------------


def test_single_node_leader_histograms_and_lease():
    async def main():
        _, nodes = make_cluster(1)
        await start_all(nodes)
        leader = await wait_for_leader(nodes)
        for i in range(5):
            await leader.apply(put(f"k{i}", i))
        obs = leader.obs
        assert obs is not None
        assert obs.append_quorum.count >= 1
        assert obs.commit_apply.count >= 1
        assert obs.leadership_gained == 1
        kinds = [ev["kind"] for ev in obs.timeline()]
        assert "election-start" in kinds and "leader-elected" in kinds
        # lease rows ride into stats()
        stats = leader.stats()
        assert "elections_started" in stats
        assert stats["leadership_gained"] == "1"
        await stop_all(nodes)
    asyncio.run(main())


def test_three_node_follower_and_peer_rows():
    async def main():
        _, nodes = make_cluster(3)
        await start_all(nodes)
        leader = await wait_for_leader(nodes)
        for i in range(5):
            await leader.apply(put(f"k{i}", i))
        await wait_until(
            lambda: all(x.last_applied >= 5 for x in nodes),
            msg="apply convergence")
        # Leader: quorum + lease ladders have content; per-peer rows
        # exist for both followers with fresh contact stamps.
        obs = leader.obs
        assert obs.append_quorum.count >= 1
        await wait_until(lambda: obs.lease_margin.count >= 1,
                         msg="lease margin samples")
        rows = {r["peer"]: r for r in obs.peer_rows(leader)}
        assert set(rows) == {x.id for x in nodes if x is not leader}
        for r in rows.values():
            assert r["last_contact_age_ms"] is not None
        await wait_until(
            lambda: all(r["match_lag_entries"] == 0
                        for r in obs.peer_rows(leader)), msg="lag drains")
        # Followers: commit→apply ladder populated via the header-commit
        # path, no leadership events.
        follower = next(x for x in nodes if not x.is_leader())
        assert follower.obs.commit_apply.count >= 1
        assert follower.obs.leadership_gained == 0
        assert any(ev["kind"] == "new-leader"
                   for ev in follower.obs.timeline())
        await stop_all(nodes)
    asyncio.run(main())


def test_deposed_leader_events_and_fail_counters():
    async def main():
        transport, nodes = make_cluster(3)
        await start_all(nodes)
        leader = await wait_for_leader(nodes)
        await leader.apply(put("a", 1))
        old = leader
        transport.isolate(old.id)
        others = [x for x in nodes if x is not old]
        new = await wait_for_leader(others)
        # The cut-off leader's replication streams count RPC failures.
        await wait_until(
            lambda: any(st["failed"] > 0
                        for st in old.obs._peers.values()),
            msg="peer_fail counts on the isolated leader")
        transport.rejoin(old.id)
        await wait_until(lambda: old.role != "Leader" and old.obs
                         .leadership_lost >= 1, msg="deposed")
        kinds = [ev["kind"] for ev in old.obs.timeline()]
        assert "leader-deposed" in kinds
        assert new.obs.leadership_gained >= 1
        await stop_all(nodes)
    asyncio.run(main())


def test_obs_compiled_out(monkeypatch):
    monkeypatch.setenv("CONSUL_TPU_RAFT_OBS", "0")
    assert not raftstats.enabled()

    async def main():
        _, nodes = make_cluster(1)
        assert all(x.obs is None for x in nodes)
        await start_all(nodes)
        leader = await wait_for_leader(nodes)
        assert await leader.apply(put("a", 1)) == 1
        assert "elections_started" not in leader.stats()
        await stop_all(nodes)
    asyncio.run(main())


# -- exposition -------------------------------------------------------------


def test_prom_families_pass_check_prom():
    async def main():
        _, nodes = make_cluster(3)
        await start_all(nodes)
        leader = await wait_for_leader(nodes)
        for i in range(5):
            await leader.apply(put(f"k{i}", i))
        hists, gauges, counters = raftstats.prom_families(leader)
        assert {f["name"] for f in hists} == {
            "consul_raft_append_quorum_ms", "consul_raft_commit_apply_ms",
            "consul_raft_snapshot_install_ms", "consul_raft_lease_margin_ms"}
        ae_h, ae_c = raftstats.aestats.families()
        text = render_prometheus([], histograms=hists + ae_h,
                                 labeled_counters=counters + ae_c,
                                 labeled_gauges=gauges)
        errors = check_text(text)
        assert errors == [], errors
        series = list(_iter_series(text))
        followers = [x.id for x in nodes if x is not leader]
        for want in ['consul_raft_append_quorum_ms_bucket{le="+Inf"}',
                     'consul_antientropy_failures_total{kind="diff"}'] + [
                f'consul_raft_peer_match_lag_entries{{peer="{p}"}}'
                for p in followers] + [
                f'consul_raft_peer_last_contact_age_ms{{peer="{p}"}}'
                for p in followers]:
            ok = _require_ok(want, series, errors)
            assert ok, f"missing {want}: {errors}"
        await stop_all(nodes)
    asyncio.run(main())


def test_telemetry_payload_shapes():
    async def main():
        _, nodes = make_cluster(1)
        await start_all(nodes)
        leader = await wait_for_leader(nodes)
        await leader.apply(put("a", 1))
        t = raftstats.telemetry(leader)
        assert t["enabled"] is True
        assert t["raft"]["state"] == "Leader"
        assert "consul_raft_append_quorum_ms" in t["histograms"]
        assert isinstance(t["timeline"], list)
        assert "antientropy" in t
        # client mode: no node at all
        t2 = raftstats.telemetry(None)
        assert "raft" not in t2 and "antientropy" in t2
        await stop_all(nodes)
    asyncio.run(main())


# -- anti-entropy stats -----------------------------------------------------


class FailingCatalogAgent:
    """LocalState's agent interface with a catalog whose register path
    always fails (per-kind failure counting)."""

    node_name = "ae-test"
    advertise_addr = "127.0.0.1"

    def cluster_size(self):
        return 1

    async def catalog_node_services(self, node):
        return {}

    async def catalog_node_checks(self, node):
        return []

    async def catalog_register(self, req):
        raise RuntimeError("catalog down")

    async def catalog_deregister(self, req):
        raise RuntimeError("catalog down")


def test_antientropy_failure_kinds_and_pending_ops(monkeypatch):
    from consul_tpu.structs.structs import NodeService

    fresh = AntiEntropyStats()
    monkeypatch.setattr(raftstats, "aestats", fresh)

    async def main():
        state = LocalState(FailingCatalogAgent())
        state.add_service(NodeService(id="web", service="web", port=80))
        assert state.pending_ops() == 1
        with pytest.raises(RuntimeError):
            await state.sync_once()
        assert fresh.failures.get("service_register") == 1
        assert fresh.syncs_total == 0          # the pass never completed
        assert state.pending_ops() == 1        # still out of sync
    asyncio.run(main())


def test_antientropy_success_path(monkeypatch):
    from consul_tpu.structs.structs import NodeService

    fresh = AntiEntropyStats()
    monkeypatch.setattr(raftstats, "aestats", fresh)

    class OkAgent(FailingCatalogAgent):
        async def catalog_register(self, req):
            return True

        async def catalog_deregister(self, req):
            return True

    async def main():
        state = LocalState(OkAgent())
        state.add_service(NodeService(id="web", service="web", port=80))
        await state.sync_once()
        assert fresh.syncs_total == 1
        assert fresh.sync.count == 1
        assert state.pending_ops() == 0
    asyncio.run(main())

    fams_h, fams_c = fresh.families()
    rows = dict((tuple(lbl.items())[0][1], v)
                for lbl, v in fams_c[0]["rows"])
    assert set(rows) == {"diff", "service_register", "service_deregister",
                         "check_register", "check_deregister"}
    assert all(v == 0.0 for v in rows.values())
