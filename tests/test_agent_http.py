"""End-to-end agent slice tests: real HTTP requests against a live agent
(SURVEY.md §4 tier 3 — the reference drives its in-process Agent's HTTP
server the same way, command/agent/*_test.go)."""

import asyncio
import base64
import socket
import struct
import threading
import time

import httpx
import pytest

from consul_tpu.agent import Agent, AgentConfig
from consul_tpu.agent.dns import (
    QTYPE_A, QTYPE_PTR, QTYPE_SRV, RCODE_NXDOMAIN, RCODE_OK, build_response,
    parse_message,
)


class AgentHarness:
    """Runs an Agent in a daemon thread with its own event loop, the way
    testutil.TestServer runs a forked binary (testutil/server.go)."""

    def __init__(self, config=None):
        self.config = config or AgentConfig(http_port=0, dns_port=0)
        self.config.http_port = 0
        self.config.dns_port = 0
        self.loop = None
        self.agent = None
        self._ready = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        self.loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self.loop)
        self.agent = Agent(self.config)
        self.loop.run_until_complete(self.agent.start())
        self._ready.set()
        self.loop.run_forever()

    def start(self):
        self._thread.start()
        assert self._ready.wait(10), "agent failed to start"
        return self

    def stop(self):
        asyncio.run_coroutine_threadsafe(self.agent.stop(), self.loop).result(5)
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(5)

    @property
    def http_addr(self):
        host, port = self.agent.http.addr
        return f"http://{host}:{port}"

    @property
    def dns_addr(self):
        return self.agent.dns.addr


@pytest.fixture(scope="module")
def harness():
    h = AgentHarness().start()
    yield h
    h.stop()


@pytest.fixture()
def client(harness):
    with httpx.Client(base_url=harness.http_addr, timeout=10) as c:
        yield c


def dns_query(addr, name, qtype=QTYPE_A):
    """Build + send a raw DNS query over UDP, parse the reply sections."""
    q = bytearray(struct.pack("!HHHHHH", 0x1234, 0x0100, 1, 0, 0, 0))
    for label in name.rstrip(".").split("."):
        q.append(len(label))
        q += label.encode()
    q.append(0)
    q += struct.pack("!HH", qtype, 1)
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.settimeout(5)
    sock.sendto(bytes(q), addr)
    buf, _ = sock.recvfrom(4096)
    sock.close()
    msg_id, flags, qd, an, ns, ar = struct.unpack("!HHHHHH", buf[:12])
    return {"rcode": flags & 0xF, "ancount": an, "arcount": ar, "raw": buf}


class TestStatus:
    def test_leader_and_peers(self, client):
        assert client.get("/v1/status/leader").json() == "node1"
        assert client.get("/v1/status/peers").json() == ["node1"]


class TestKV:
    def test_put_get_delete(self, client):
        assert client.put("/v1/kv/foo", content=b"bar").json() is True
        resp = client.get("/v1/kv/foo")
        ent = resp.json()[0]
        assert base64.b64decode(ent["Value"]) == b"bar"
        assert ent["Key"] == "foo"
        assert int(resp.headers["X-Consul-Index"]) == ent["ModifyIndex"]
        assert client.get("/v1/kv/foo?raw").content == b"bar"
        assert client.delete("/v1/kv/foo").json() is True
        assert client.get("/v1/kv/foo").status_code == 404

    def test_flags_and_cas(self, client):
        client.put("/v1/kv/cask?flags=42", content=b"a")
        ent = client.get("/v1/kv/cask").json()[0]
        assert ent["Flags"] == 42
        idx = ent["ModifyIndex"]
        assert client.put(f"/v1/kv/cask?cas={idx - 1}", content=b"x").json() is False
        assert client.put(f"/v1/kv/cask?cas={idx}", content=b"b").json() is True
        assert client.delete(f"/v1/kv/cask?cas={idx - 1}").json() is False

    def test_recurse_and_keys(self, client):
        for k in ("web/a", "web/b/c", "zother"):
            client.put(f"/v1/kv/{k}", content=b"x")
        ents = client.get("/v1/kv/web/?recurse").json()
        assert [e["Key"] for e in ents] == ["web/a", "web/b/c"]
        keys = client.get("/v1/kv/web/?keys&separator=/").json()
        assert keys == ["web/a", "web/b/"]
        assert client.delete("/v1/kv/web/?recurse").json() is True
        r = client.get("/v1/kv/web/?recurse")
        assert r.status_code == 404
        # tombstone keeps index advancing for blocking queries
        assert int(r.headers["X-Consul-Index"]) > 0

    def test_blocking_query_wakes_on_write(self, harness, client):
        client.put("/v1/kv/blk", content=b"v1")
        idx = int(client.get("/v1/kv/blk").headers["X-Consul-Index"])

        def write_later():
            time.sleep(0.2)
            httpx.put(f"{harness.http_addr}/v1/kv/blk", content=b"v2", timeout=5)

        t = threading.Thread(target=write_later)
        start = time.monotonic()
        t.start()
        resp = client.get(f"/v1/kv/blk?index={idx}&wait=10s")
        elapsed = time.monotonic() - start
        t.join()
        assert base64.b64decode(resp.json()[0]["Value"]) == b"v2"
        assert 0.1 < elapsed < 5

    def test_blocking_query_timeout(self, client):
        client.put("/v1/kv/blk2", content=b"v")
        idx = int(client.get("/v1/kv/blk2").headers["X-Consul-Index"])
        start = time.monotonic()
        resp = client.get(f"/v1/kv/blk2?index={idx}&wait=300ms")
        assert time.monotonic() - start < 2
        assert int(resp.headers["X-Consul-Index"]) == idx

    def test_stale_and_consistent_conflict(self, client):
        assert client.get("/v1/kv/foo?stale&consistent").status_code == 400


class TestCatalog:
    def test_register_and_queries(self, client):
        reg = {
            "Node": "ext1", "Address": "10.1.2.3",
            "Service": {"Service": "web", "Tags": ["v1"], "Port": 8080},
            "Check": {"Name": "web alive", "Status": "passing",
                      "ServiceID": "web"},
        }
        assert client.put("/v1/catalog/register", json=reg).json() is True
        nodes = client.get("/v1/catalog/nodes").json()
        assert {n["Node"] for n in nodes} >= {"node1", "ext1"}
        services = client.get("/v1/catalog/services").json()
        assert "web" in services and "consul" in services
        sn = client.get("/v1/catalog/service/web").json()
        assert sn[0]["ServiceName"] == "web" and sn[0]["ServicePort"] == 8080
        ns = client.get("/v1/catalog/node/ext1").json()
        assert ns["Node"]["Address"] == "10.1.2.3"
        assert "web" in ns["Services"]
        assert client.get("/v1/catalog/datacenters").json() == ["dc1"]

    def test_register_validation(self, client):
        assert client.put("/v1/catalog/register", json={"Node": "x"}).status_code == 400

    def test_deregister(self, client):
        reg = {"Node": "bye", "Address": "10.0.0.9"}
        client.put("/v1/catalog/register", json=reg)
        assert client.put("/v1/catalog/deregister", json={"Node": "bye"}).json() is True
        assert all(n["Node"] != "bye"
                   for n in client.get("/v1/catalog/nodes").json())


class TestHealth:
    def test_health_queries(self, client):
        reg = {
            "Node": "hnode", "Address": "10.2.0.1",
            "Service": {"Service": "db", "Port": 5432},
            "Checks": [
                {"Name": "db ok", "CheckID": "db:ok", "Status": "passing",
                 "ServiceID": "db"},
                {"Name": "disk", "CheckID": "disk", "Status": "warning"},
            ],
        }
        client.put("/v1/catalog/register", json=reg)
        checks = client.get("/v1/health/node/hnode").json()
        assert {c["CheckID"] for c in checks} == {"db:ok", "disk"}
        svc_checks = client.get("/v1/health/checks/db").json()
        assert svc_checks[0]["CheckID"] == "db:ok"
        warn = client.get("/v1/health/state/warning").json()
        assert any(c["CheckID"] == "disk" for c in warn)
        csn = client.get("/v1/health/service/db").json()
        assert csn[0]["Node"]["Node"] == "hnode"
        assert {c["CheckID"] for c in csn[0]["Checks"]} == {"db:ok", "disk"}

    def test_passing_filter(self, client):
        reg = {
            "Node": "pnode", "Address": "10.2.0.2",
            "Service": {"Service": "cache"},
            "Check": {"Name": "c", "CheckID": "cache:c", "Status": "critical",
                      "ServiceID": "cache"},
        }
        client.put("/v1/catalog/register", json=reg)
        assert client.get("/v1/health/service/cache").json() != []
        assert client.get("/v1/health/service/cache?passing").json() == []


class TestSessions:
    def test_session_lifecycle_and_locks(self, client):
        sid = client.put("/v1/session/create", json={}).json()["ID"]
        assert len(sid) == 36
        info = client.get(f"/v1/session/info/{sid}").json()
        assert info[0]["Node"] == "node1"
        # acquire/release via KV
        assert client.put(f"/v1/kv/lockk?acquire={sid}", content=b"me").json() is True
        ent = client.get("/v1/kv/lockk").json()[0]
        assert ent["Session"] == sid and ent["LockIndex"] == 1
        sid2 = client.put("/v1/session/create", json={}).json()["ID"]
        assert client.put(f"/v1/kv/lockk?acquire={sid2}", content=b"you").json() is False
        assert client.put(f"/v1/kv/lockk?release={sid}", content=b"").json() is True
        assert client.put("/v1/session/destroy/" + sid).json() is True
        assert client.get(f"/v1/session/info/{sid}").json() == []
        sessions = client.get("/v1/session/list").json()
        assert any(s["ID"] == sid2 for s in sessions)
        node_sessions = client.get("/v1/session/node/node1").json()
        assert any(s["ID"] == sid2 for s in node_sessions)

    def test_session_ttl_validation(self, client):
        r = client.put("/v1/session/create", json={"TTL": "1s"})
        assert r.status_code == 400  # below min 10s
        r = client.put("/v1/session/create", json={"TTL": "30s"})
        assert r.status_code == 200


class TestAgentEndpoints:
    def test_self(self, client):
        me = client.get("/v1/agent/self").json()
        assert me["Config"]["NodeName"] == "node1"
        assert me["Config"]["Server"] is True
        assert me["Stats"]["raft"]["state"] == "Leader"

    def test_services_checks_members(self, client):
        services = client.get("/v1/agent/services").json()
        assert "consul" in services
        checks = client.get("/v1/agent/checks").json()
        assert "serfHealth" in checks
        members = client.get("/v1/agent/members").json()
        assert members[0]["Name"] == "node1"


class TestUI:
    def test_ui_endpoints(self, client):
        nodes = client.get("/v1/internal/ui/nodes").json()
        assert any(n["Node"] == "node1" for n in nodes)
        info = client.get("/v1/internal/ui/node/node1").json()
        assert info["Node"] == "node1"
        services = client.get("/v1/internal/ui/services").json()
        assert any(s["Name"] == "consul" for s in services)


class TestDNS:
    def test_node_a_lookup(self, harness, client):
        client.put("/v1/catalog/register",
                   json={"Node": "dnsnode", "Address": "10.9.9.9"})
        r = dns_query(harness.dns_addr, "dnsnode.node.consul")
        assert r["rcode"] == RCODE_OK and r["ancount"] == 1
        assert bytes([10, 9, 9, 9]) in r["raw"]

    def test_node_with_dc(self, harness):
        r = dns_query(harness.dns_addr, "dnsnode.node.dc1.consul")
        assert r["rcode"] == RCODE_OK and r["ancount"] == 1
        r = dns_query(harness.dns_addr, "dnsnode.node.dc9.consul")
        assert r["rcode"] == RCODE_NXDOMAIN

    def test_service_lookup_filters_critical(self, harness, client):
        for i, status in enumerate(["passing", "passing", "critical"]):
            client.put("/v1/catalog/register", json={
                "Node": f"d{i}", "Address": f"10.8.0.{i + 1}",
                "Service": {"Service": "dsvc", "Port": 100 + i},
                "Check": {"Name": "c", "CheckID": "dc", "Status": status,
                          "ServiceID": "dsvc"},
            })
        r = dns_query(harness.dns_addr, "dsvc.service.consul")
        assert r["rcode"] == RCODE_OK and r["ancount"] == 2

    def test_srv_lookup(self, harness):
        r = dns_query(harness.dns_addr, "dsvc.service.consul", QTYPE_SRV)
        assert r["rcode"] == RCODE_OK
        assert r["ancount"] == 2 and r["arcount"] == 2

    def test_rfc2782(self, harness):
        r = dns_query(harness.dns_addr, "_dsvc._tcp.service.consul", QTYPE_SRV)
        assert r["ancount"] == 2

    def test_udp_answer_cap(self, harness, client):
        for i in range(6):
            client.put("/v1/catalog/register", json={
                "Node": f"many{i}", "Address": f"10.7.0.{i + 1}",
                "Service": {"Service": "many", "Port": 80},
            })
        r = dns_query(harness.dns_addr, "many.service.consul")
        assert r["ancount"] == 3  # dns.go UDP cap
        # default: capped silently, no TC bit (avoids TCP retries)
        assert not struct.unpack("!H", r["raw"][2:4])[0] & 0x0200

    def test_nxdomain(self, harness):
        assert dns_query(harness.dns_addr, "ghost.service.consul")["rcode"] == RCODE_NXDOMAIN

    def test_ptr_lookup(self, harness, client):
        """dig -x equivalent (handlePtr, dns.go:164-217)."""
        client.put("/v1/catalog/register",
                   json={"Node": "revnode", "Address": "10.11.12.13"})
        r = dns_query(harness.dns_addr, "13.12.11.10.in-addr.arpa",
                      QTYPE_PTR)
        assert r["rcode"] == RCODE_OK and r["ancount"] == 1
        # rdata carries the FQDN as DNS labels
        assert b"\x07revnode\x04node" in r["raw"]

    def test_ptr_unknown_address(self, harness):
        # 203.0.113.0/24 is TEST-NET; no registered node has it (the
        # agent itself sits on 127.0.0.1, which WOULD match)
        r = dns_query(harness.dns_addr, "77.113.0.203.in-addr.arpa",
                      QTYPE_PTR)
        assert r["rcode"] == RCODE_NXDOMAIN

    def test_udp_cap_sets_tc_when_enabled(self):
        """enable_truncate advertises the UDP cut with the TC bit
        (DNSConfig.EnableTruncate role)."""
        h = AgentHarness(AgentConfig(http_port=0, dns_port=0,
                                     dns_enable_truncate=True)).start()
        try:
            with httpx.Client(base_url=h.http_addr, timeout=10) as c:
                for i in range(6):
                    c.put("/v1/catalog/register", json={
                        "Node": f"tc{i}", "Address": f"10.6.0.{i + 1}",
                        "Service": {"Service": "tcsvc", "Port": 80}})
            r = dns_query(h.dns_addr, "tcsvc.service.consul")
            assert r["ancount"] == 3
            flags = struct.unpack("!H", r["raw"][2:4])[0]
            assert flags & 0x0200, "TC bit not set despite enable_truncate"
        finally:
            h.stop()

    def test_out_of_domain_refused_without_recursors(self, harness):
        from consul_tpu.agent.dns import RCODE_REFUSED
        r = dns_query(harness.dns_addr, "example.com")
        assert r["rcode"] == RCODE_REFUSED


class TestDNSStale:
    def test_max_stale_requeries_leader(self):
        """allow_stale + last_contact beyond max_stale must retry the
        read without AllowStale (dns.go:360-372)."""
        import asyncio

        from consul_tpu.agent.dns import DNSServer
        from consul_tpu.structs.structs import QueryMeta

        calls = []

        class FakeInternal:
            async def node_info(self, node, opts):
                calls.append(opts.allow_stale)
                meta = QueryMeta(index=1)
                if opts.allow_stale:
                    meta.last_contact = 99.0  # very stale follower
                    return meta, [{"node": node, "address": "10.0.0.1"}]
                meta.last_contact = 0.0
                return meta, [{"node": node, "address": "10.0.0.2"}]

        class FakeServer:
            internal = FakeInternal()

            class config:
                datacenter = "dc1"

        class FakeAgent:
            server = FakeServer()

        dns = DNSServer(FakeAgent(), allow_stale=True, max_stale=5.0)

        async def run():
            return await dns._node_lookup(
                parse_message(b""), type("Q", (), {"name": "n1.node.consul."})(),
                "n1", udp=True)

        # build a real query for parse; simpler: call _requery directly
        async def direct():
            async def reader(opts):
                return await FakeAgent.server.internal.node_info("n1", opts)
            return await dns._requery(reader)

        meta, dump = asyncio.run(direct())
        assert calls == [True, False], calls       # stale, then leader retry
        assert dump[0]["address"] == "10.0.0.2"    # leader's answer wins

    def test_fresh_stale_answer_not_requeried(self):
        import asyncio

        from consul_tpu.agent.dns import DNSServer
        from consul_tpu.structs.structs import QueryMeta

        calls = []

        class FakeAgent:
            server = None

        dns = DNSServer(FakeAgent(), allow_stale=True, max_stale=5.0)

        async def reader(opts):
            calls.append(opts.allow_stale)
            m = QueryMeta(index=1)
            m.last_contact = 0.3  # fresh enough
            return m, ["x"]

        asyncio.run(dns._requery(reader))
        assert calls == [True]


class TestDNSRecursor:
    def test_forwards_to_recursor(self):
        """Out-of-domain queries forward to the configured recursor and
        its answer is relayed verbatim (handleRecurse, dns.go:618-656)."""
        # fake upstream: answers any query with a fixed A record
        upstream = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        upstream.bind(("127.0.0.1", 0))
        upstream.settimeout(10)
        up_addr = upstream.getsockname()

        def serve_one():
            buf, addr = upstream.recvfrom(4096)
            msg = parse_message(buf)
            from consul_tpu.agent.dns import a_record
            rec = a_record(msg.questions[0].name, "93.184.216.34", 60)
            upstream.sendto(
                build_response(msg, RCODE_OK, [rec], authoritative=False),
                addr)

        t = threading.Thread(target=serve_one, daemon=True)
        t.start()
        h = AgentHarness(AgentConfig(
            http_port=0, dns_port=0,
            recursors=[f"127.0.0.1:{up_addr[1]}"])).start()
        try:
            r = dns_query(h.dns_addr, "example.com")
            assert r["rcode"] == RCODE_OK and r["ancount"] == 1
            assert bytes([93, 184, 216, 34]) in r["raw"]
        finally:
            h.stop()
            upstream.close()
