"""FSM apply/snapshot/restore (reference tier: consul/fsm_test.go)."""

import pytest

from consul_tpu.consensus.fsm import ConsulFSM, IGNORE_UNKNOWN_FLAG
from consul_tpu.structs import codec
from consul_tpu.structs.structs import (
    ACL,
    ACLOp,
    ACLRequest,
    DeregisterRequest,
    DirEntry,
    HEALTH_PASSING,
    HealthCheck,
    KVSOp,
    KVSRequest,
    MessageType,
    NodeService,
    RegisterRequest,
    Session,
    SessionOp,
    SessionRequest,
    TombstoneRequest,
)


def apply(fsm, index, msg_type, req):
    return fsm.apply(index, codec.encode(int(msg_type), req))


def seed(fsm):
    apply(fsm, 1, MessageType.REGISTER, RegisterRequest(
        node="n1", address="10.0.0.1",
        service=NodeService(id="web", service="web", tags=["v1"], port=80),
        check=HealthCheck(node="n1", check_id="c1", name="c",
                          status=HEALTH_PASSING, service_id="web")))
    apply(fsm, 2, MessageType.KVS, KVSRequest(
        op=KVSOp.SET.value, dir_ent=DirEntry(key="k1", value=b"v1")))
    apply(fsm, 3, MessageType.SESSION, SessionRequest(
        op=SessionOp.CREATE.value, session=Session(id="sess-1", node="n1")))
    apply(fsm, 4, MessageType.ACL, ACLRequest(
        op=ACLOp.SET.value, acl=ACL(id="acl-1", name="t", rules="")))


class TestApply:
    def test_register_deregister(self):
        fsm = ConsulFSM()
        seed(fsm)
        assert fsm.store.get_node("n1")[1] == "10.0.0.1"
        apply(fsm, 5, MessageType.DEREGISTER, DeregisterRequest(node="n1", check_id="c1"))
        assert fsm.store.node_checks("n1")[1] == []
        apply(fsm, 6, MessageType.DEREGISTER, DeregisterRequest(node="n1", service_id="web"))
        assert fsm.store.service_nodes("web")[1] == []
        apply(fsm, 7, MessageType.DEREGISTER, DeregisterRequest(node="n1"))
        assert fsm.store.get_node("n1")[1] is None

    def test_kvs_ops_return_bools(self):
        fsm = ConsulFSM()
        seed(fsm)
        assert apply(fsm, 5, MessageType.KVS, KVSRequest(
            op=KVSOp.CAS.value, dir_ent=DirEntry(key="k1", value=b"x",
                                                 modify_index=2))) is True
        assert apply(fsm, 6, MessageType.KVS, KVSRequest(
            op=KVSOp.CAS.value, dir_ent=DirEntry(key="k1", value=b"y",
                                                 modify_index=1))) is False
        assert apply(fsm, 7, MessageType.KVS, KVSRequest(
            op=KVSOp.LOCK.value, dir_ent=DirEntry(key="k1", value=b"l",
                                                  session="sess-1"))) is True
        assert apply(fsm, 8, MessageType.KVS, KVSRequest(
            op=KVSOp.UNLOCK.value, dir_ent=DirEntry(key="k1", value=b"u",
                                                    session="sess-1"))) is True

    def test_tombstone_reap(self):
        fsm = ConsulFSM()
        seed(fsm)
        apply(fsm, 5, MessageType.KVS, KVSRequest(
            op=KVSOp.DELETE.value, dir_ent=DirEntry(key="k1")))
        assert fsm.store.kvs_list("k")[0] == 5
        apply(fsm, 6, MessageType.TOMBSTONE, TombstoneRequest(reap_index=5))
        assert fsm.store.kvs_list("k")[0] == 0

    def test_unknown_type(self):
        fsm = ConsulFSM()
        with pytest.raises(ValueError):
            fsm.apply(1, bytes([99]) + b"\x80")
        # ignore-flagged unknown type is skipped silently
        assert fsm.apply(1, bytes([99 | IGNORE_UNKNOWN_FLAG]) + b"\x80") is None


class TestSnapshot:
    def test_round_trip(self):
        fsm = ConsulFSM()
        seed(fsm)
        fsm.store.kvs_delete(5, "k1")  # leave a tombstone
        snap = fsm.snapshot(last_index=5)

        fsm2 = ConsulFSM()
        assert fsm2.restore(snap) == 5
        assert fsm2.store.get_node("n1")[1] == "10.0.0.1"
        _, sns = fsm2.store.service_nodes("web")
        assert sns[0].service_port == 80 and sns[0].service_tags == ["v1"]
        _, checks = fsm2.store.node_checks("n1")
        assert checks[0].status == HEALTH_PASSING
        assert fsm2.store.session_get("sess-1")[1].node == "n1"
        assert fsm2.store.acl_get("acl-1")[1].name == "t"
        assert fsm2.store.kvs_list("k")[0] == 5  # tombstone survived

    def test_snapshot_deterministic(self):
        a, b = ConsulFSM(), ConsulFSM()
        for fsm in (a, b):
            seed(fsm)
        assert a.snapshot(4) == b.snapshot(4)

    def test_restore_replaces_state(self):
        fsm = ConsulFSM()
        seed(fsm)
        snap = fsm.snapshot(4)
        apply(fsm, 5, MessageType.KVS, KVSRequest(
            op=KVSOp.SET.value, dir_ent=DirEntry(key="extra", value=b"z")))
        fsm.restore(snap)
        assert fsm.store.kvs_get("extra")[1] is None
        assert fsm.store.kvs_get("k1")[1].value == b"v1"
