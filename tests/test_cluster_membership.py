"""Full-stack cluster tests: real agents, real gossip, real RPC mesh.

The round-2 acceptance tier (VERDICT items 3-4; reference shape:
consul/leader_test.go reconciliation + testutil cluster bring-up):
three agents on loopback with bootstrap-expect self-assembly,
gossip-driven membership feeding the leader's catalog reconcile, kill
and leave choreography, and HTTP visibility of the serfHealth verdict.
"""

import asyncio

import pytest

from consul_tpu.agent.agent import Agent, AgentConfig
from consul_tpu.consensus.raft import RaftConfig
from consul_tpu.structs.structs import (
    HEALTH_CRITICAL, HEALTH_PASSING, SERF_CHECK_ID)

FAST_RAFT = RaftConfig(heartbeat_interval=0.03, election_timeout_min=0.06,
                       election_timeout_max=0.12, rpc_timeout=0.5)
TIMING = dict(probe_interval=0.05, probe_timeout=0.02, gossip_interval=0.02,
              suspicion_mult=3.0, push_pull_interval=0.5, reap_interval=0.2)


@pytest.fixture()
def loop():
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    yield loop
    loop.close()


async def _wait(cond, timeout=15.0, interval=0.03):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if cond():
            return True
        await asyncio.sleep(interval)
    return False


async def _mk_agent(name, seeds=(), expect=3, server=True, **kw):
    cfg = AgentConfig(
        node_name=name, server=server,
        bootstrap=False if expect else not server,
        bootstrap_expect=expect if server else 0,
        rpc_mesh_port=0, http_port=0, dns_port=0,
        serf_timing=dict(TIMING), raft_config=FAST_RAFT,
        reconcile_interval=0.3, **kw)
    a = Agent(cfg)
    await a.start()
    if seeds:
        assert await a.join(list(seeds)) > 0
    return a


def _lan_seed(agent):
    return [f"127.0.0.1:{agent.lan_pool.local_addr[1]}"]


async def _mk_cluster(n=3):
    first = await _mk_agent("s1", expect=n)
    agents = [first]
    for i in range(2, n + 1):
        agents.append(await _mk_agent(f"s{i}", seeds=_lan_seed(first),
                                      expect=n))
    assert await _wait(lambda: any(a.server.is_leader() for a in agents)), \
        "no leader elected after bootstrap-expect assembly"
    return agents


def _leader(agents):
    return next(a for a in agents if a.server.is_leader())


def _serf_health(agent, node):
    _, checks = agent.server.store.node_checks(node)
    for c in checks:
        if c.check_id == SERF_CHECK_ID:
            return c.status
    return None


class TestClusterFormation:
    def test_three_agents_assemble_and_reconcile(self, loop):
        async def body():
            agents = await _mk_cluster(3)
            # members parity: every agent sees 3 alive LAN members with
            # the consul server tag scheme
            for a in agents:
                assert await _wait(
                    lambda a=a: len([m for m in a.lan_members()
                                     if m["Status"] == "alive"]) == 3)
                m = a.lan_members()[0]
                assert m["Tags"]["role"] == "consul"
                assert m["Tags"]["dc"] == "dc1"
            # raft assembled the same 3-node peer set everywhere
            for a in agents:
                assert sorted(a.server.raft.peers) == ["s1", "s2", "s3"]
            # the leader's reconcile registers every node in the catalog
            # with a passing serfHealth (leader.go:354-421)
            leader = _leader(agents)
            assert await _wait(
                lambda: all(_serf_health(leader, f"s{i}") == HEALTH_PASSING
                            for i in (1, 2, 3)))
            # replicated: followers serve the same catalog
            follower = next(a for a in agents if not a.server.is_leader())
            assert await _wait(
                lambda: all(_serf_health(follower, f"s{i}") == HEALTH_PASSING
                            for i in (1, 2, 3)))
            for a in agents:
                await a.stop()
        loop.run_until_complete(body())

    def test_kill_node_goes_critical_in_catalog_via_http(self, loop):
        async def body():
            import aiohttp
            agents = await _mk_cluster(3)
            victim = next(a for a in agents if not a.server.is_leader())
            victim_name = a_name = victim.config.node_name
            survivors = [a for a in agents if a is not victim]
            await victim.stop()  # hard kill: no leave broadcast
            leader = _leader(survivors)
            assert await _wait(
                lambda: _serf_health(leader, victim_name) == HEALTH_CRITICAL,
                timeout=20), "serfHealth never went critical"
            # visible over the HTTP surface (GET /v1/health/node/<node>);
            # poll: the queried agent's FSM applies the critical register
            # a replication beat after the leader commits it
            host, port = survivors[0].http.addr
            deadline = asyncio.get_event_loop().time() + 10
            serf = []
            async with aiohttp.ClientSession() as s:
                while asyncio.get_event_loop().time() < deadline:
                    async with s.get(f"http://{host}:{port}"
                                     f"/v1/health/node/{a_name}") as r:
                        body_json = await r.json()
                    serf = [c for c in body_json
                            if c["CheckID"] == SERF_CHECK_ID]
                    if serf and serf[0]["Status"] == HEALTH_CRITICAL:
                        break
                    await asyncio.sleep(0.05)
            assert serf and serf[0]["Status"] == HEALTH_CRITICAL
            for a in survivors:
                await a.stop()
        loop.run_until_complete(body())

    def test_graceful_leave_deregisters(self, loop):
        async def body():
            agents = await _mk_cluster(3)
            leaver = next(a for a in agents if not a.server.is_leader())
            name = leaver.config.node_name
            survivors = [a for a in agents if a is not leaver]
            await leaver.graceful_leave()
            await leaver.stop()
            leader = _leader(survivors)
            # left members deregister entirely (handleLeftMember,
            # leader.go:462-501) once the reaper forgets them
            def gone():
                _, addr = leader.server.store.get_node(name)
                return addr is None
            assert await _wait(gone, timeout=20), \
                "left node still in catalog"
            # and it left the raft peer set (removeConsulServer)
            assert await _wait(
                lambda: name not in leader.server.raft.peers, timeout=10)
            for a in survivors:
                await a.stop()
        loop.run_until_complete(body())


class TestClusterRPC:
    def test_kv_write_via_follower_agent(self, loop):
        async def body():
            from consul_tpu.structs.structs import (
                DirEntry, KVSOp, KVSRequest)
            agents = await _mk_cluster(3)
            follower = next(a for a in agents if not a.server.is_leader())
            ok = await follower.server.kvs.apply(KVSRequest(
                op=KVSOp.SET.value,
                dir_ent=DirEntry(key="cluster-key", value=b"v")))
            assert ok
            leader = _leader(agents)
            assert await _wait(
                lambda: leader.server.store.kvs_get("cluster-key")[1]
                is not None)
            for a in agents:
                await a.stop()
        loop.run_until_complete(body())

    def test_user_event_floods_cluster(self, loop):
        async def body():
            from consul_tpu.structs.structs import UserEvent
            agents = await _mk_cluster(3)
            await agents[0].broadcast_event(UserEvent(name="deploy",
                                                      payload=b"v9"))
            def all_got():
                return all(any(e.name == "deploy" and e.payload == b"v9"
                               for e in a.events.events())
                           for a in agents)
            assert await _wait(all_got), "event did not flood to all agents"
            for a in agents:
                await a.stop()
        loop.run_until_complete(body())
