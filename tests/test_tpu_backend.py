"""The graft tier: gossip_backend=tpu vs the asyncio SWIM backend.

The same membership behaviors — join visibility, abrupt-death failure
detection, graceful leave, user events, failed-node rejoin — run
against BOTH backends behind the serf boundary:

* ``swim`` — per-agent asyncio memberlist (membership/swim.py)
* ``tpu``  — the kernel session in the gossip plane
  (gossip/plane.py + membership/tpu_backend.py over the C++ bridge)

If the two backends diverge in what the agent observes, the graft has
broken the boundary contract (consul/server.go:284-325 + serf event
channel).  Failure detection on the tpu backend is decided by the SWIM
kernel's on-device suspicion/Lifeguard dynamics — the plane only feeds
it the heartbeat-lapse probe signal.
"""

import asyncio

import pytest

from consul_tpu.gossip.plane import GossipPlane, PlaneConfig
from consul_tpu.membership.serf import SerfConfig, SerfPool
from consul_tpu.membership.swim import (EV_FAILED, EV_JOIN, EV_LEAVE,
                                        STATE_ALIVE, STATE_DEAD)
from consul_tpu.membership.tpu_backend import TpuSerfPool

BACKENDS = ("swim", "tpu")


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


async def _wait(cond, timeout=20.0, interval=0.02):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if cond():
            return True
        await asyncio.sleep(interval)
    return cond()


def _fast_cfg(name: str) -> SerfConfig:
    return SerfConfig(node_name=name, bind_addr="127.0.0.1",
                      tags={"role": "node", "dc": "dc1"},
                      probe_interval=0.05, probe_timeout=0.02,
                      gossip_interval=0.02, suspicion_mult=3.0,
                      push_pull_interval=1.0)


class Cluster:
    """Uniform harness: N pools over one backend, recorded events."""

    def __init__(self, backend: str) -> None:
        self.backend = backend
        self.plane = None
        self.pools = {}
        self.events = {}

    async def start(self, names) -> None:
        if self.backend == "tpu":
            self.plane = GossipPlane(PlaneConfig(
                bind_port=0, capacity=32, slots=16,
                gossip_interval_s=0.02, probe_every=5,
                suspicion_mult=1.0, hb_lapse_s=0.3))
            await self.plane.start()
        first_addr = None
        for name in names:
            ev = []
            self.events[name] = ev

            def on_event(kind, payload, _ev=ev):
                _ev.append((kind, payload))

            if self.backend == "tpu":
                addr = "127.0.0.1:%d" % self.plane.local_addr[1]
                pool = TpuSerfPool(_fast_cfg(name), on_event=on_event,
                                   plane_addr=addr)
                await pool.start()
            else:
                pool = SerfPool(_fast_cfg(name), on_event=on_event)
                await pool.start()
                if first_addr is not None:
                    await pool.join([first_addr])
                first_addr = first_addr or (
                    "127.0.0.1:%d" % pool.local_addr[1])
            self.pools[name] = pool

    async def kill(self, name: str) -> None:
        """Abrupt death: transport stops, no leave message."""
        pool = self.pools.pop(name)
        if self.backend == "tpu":
            await pool.stop()          # closes bridge -> heartbeats stop
        else:
            await pool.ml.stop()       # sockets down mid-protocol
        self.events.pop(name, None)

    async def stop(self) -> None:
        for pool in self.pools.values():
            try:
                await pool.stop()
            except Exception:
                pass
        if self.plane is not None:
            await self.plane.stop()

    def member_states(self, viewer: str):
        return {n.name: n.state for n in self.pools[viewer].members()}


@pytest.mark.slow
@pytest.mark.timeout_s(300)
@pytest.mark.parametrize("backend", BACKENDS)
def test_join_visibility(loop, backend):
    async def body():
        c = Cluster(backend)
        try:
            await c.start(["a", "b", "c"])
            for viewer in ("a", "b", "c"):
                assert await _wait(lambda v=viewer: {
                    k for k, s in c.member_states(v).items()
                    if s == STATE_ALIVE} >= {"a", "b", "c"}), \
                    (viewer, c.member_states(viewer))
        finally:
            await c.stop()
    loop.run_until_complete(body())


@pytest.mark.slow
@pytest.mark.timeout_s(300)
@pytest.mark.parametrize("backend", BACKENDS)
def test_abrupt_death_detected(loop, backend):
    async def body():
        c = Cluster(backend)
        try:
            await c.start(["a", "b", "c"])
            assert await _wait(
                lambda: len(c.pools["a"].alive_members()) == 3)
            await c.kill("c")
            # The failure detector (kernel suspicion/Lifeguard on tpu;
            # probe/suspect timers on swim) must declare c dead and
            # surface EV_FAILED through the serf boundary.
            assert await _wait(lambda: any(
                k == EV_FAILED and n.name == "c"
                for k, n in c.events["a"]), timeout=30.0), \
                [k for k, _ in c.events["a"]]
            assert c.member_states("a").get("c") == STATE_DEAD
        finally:
            await c.stop()
    loop.run_until_complete(body())


@pytest.mark.slow
@pytest.mark.timeout_s(300)
@pytest.mark.parametrize("backend", BACKENDS)
def test_graceful_leave(loop, backend):
    async def body():
        c = Cluster(backend)
        try:
            await c.start(["a", "b"])
            assert await _wait(
                lambda: len(c.pools["a"].alive_members()) == 2)
            await c.pools["b"].leave()
            assert await _wait(lambda: any(
                k == EV_LEAVE and n.name == "b"
                for k, n in c.events["a"])), \
                [k for k, _ in c.events["a"]]
            # a left member is not failed — no EV_FAILED for b
            assert not any(k == EV_FAILED and n.name == "b"
                           for k, n in c.events["a"])
        finally:
            await c.stop()
    loop.run_until_complete(body())


@pytest.mark.slow
@pytest.mark.timeout_s(300)
@pytest.mark.parametrize("backend", BACKENDS)
def test_user_events_flood(loop, backend):
    async def body():
        c = Cluster(backend)
        try:
            await c.start(["a", "b", "c"])
            assert await _wait(
                lambda: len(c.pools["a"].alive_members()) == 3)
            c.pools["a"].user_event("deploy", b"v2")

            def got(name):
                return any(k == "user" and p.get("name") == "deploy"
                           and p.get("payload") == b"v2"
                           for k, p in c.events[name])
            assert await _wait(lambda: got("b") and got("c")), \
                {n: [k for k, _ in evs] for n, evs in c.events.items()}
        finally:
            await c.stop()
    loop.run_until_complete(body())


@pytest.mark.slow
@pytest.mark.timeout_s(300)
def test_tpu_failed_node_rejoins(loop):
    """Heartbeats resuming after a dead verdict = serf failed->rejoin:
    the plane re-admits the id and the cluster sees a fresh join."""
    async def body():
        c = Cluster("tpu")
        try:
            await c.start(["a", "b"])
            assert await _wait(
                lambda: len(c.pools["a"].alive_members()) == 2)
            await c.kill("b")
            assert await _wait(lambda: any(
                k == EV_FAILED and n.name == "b"
                for k, n in c.events["a"]), timeout=30.0)
            # b comes back (new process, same name)
            ev_b2 = []
            addr = "127.0.0.1:%d" % c.plane.local_addr[1]
            b2 = TpuSerfPool(_fast_cfg("b"),
                             on_event=lambda k, p: ev_b2.append((k, p)),
                             plane_addr=addr)
            await b2.start()
            c.pools["b"] = b2
            c.events["b"] = ev_b2
            assert await _wait(lambda: any(
                k == EV_JOIN and n.name == "b"
                for k, n in c.events["a"][::-1])), \
                [k for k, _ in c.events["a"]]
            assert await _wait(
                lambda: c.member_states("a").get("b") == STATE_ALIVE)
        finally:
            await c.stop()
    loop.run_until_complete(body())


@pytest.mark.slow
@pytest.mark.timeout_s(300)
def test_tpu_backend_uses_native_bridge(loop):
    """The C++ bridge (native/gbridge.cpp) is the production transport;
    this asserts it actually built and carried the session."""
    from consul_tpu.native.bridge import native_available
    assert native_available(), "gbridge build failed"

    async def body():
        c = Cluster("tpu")
        try:
            await c.start(["a"])
            assert c.pools["a"]._native, "fell back to asyncio transport"
        finally:
            await c.stop()
    loop.run_until_complete(body())


@pytest.mark.slow
@pytest.mark.timeout_s(300)
def test_tpu_asyncio_fallback_transport(loop):
    """Bridge parity: the pure-asyncio fallback speaks the same wire
    protocol (for toolchain-less hosts)."""
    async def body():
        plane = GossipPlane(PlaneConfig(
            bind_port=0, capacity=8, slots=8, gossip_interval_s=0.02,
            suspicion_mult=1.0, hb_lapse_s=0.3))
        await plane.start()
        addr = "127.0.0.1:%d" % plane.local_addr[1]
        ev = []
        pool = TpuSerfPool(_fast_cfg("solo"),
                           on_event=lambda k, p: ev.append((k, p)),
                           plane_addr=addr, use_native=False)
        try:
            await pool.start()
            assert not pool._native
            assert await _wait(lambda: any(
                k == EV_JOIN and n.name == "solo" for k, n in ev))
        finally:
            await pool.stop()
            await plane.stop()
    loop.run_until_complete(body())


@pytest.mark.slow
@pytest.mark.timeout_s(300)
def test_hybrid_universe_sim_nodes(loop):
    """The hybrid posture: real agents share the kernel arrays with a
    simulated swarm (PlaneConfig.sim_nodes).  Sim nodes are kernel
    members — they probe, relay rumors, and count toward dissemination
    — but are invisible to the agents' members view (they are not
    registered catalog nodes).  Failure detection of a real agent must
    still work with the swarm present."""
    async def body():
        plane = GossipPlane(PlaneConfig(
            bind_port=0, capacity=16, sim_nodes=240, slots=16,
            gossip_interval_s=0.02, probe_every=5,
            suspicion_mult=1.0, hb_lapse_s=0.3))
        await plane.start()
        import numpy as np
        assert int(np.asarray(plane._state.member).sum()) == 240
        addr = "127.0.0.1:%d" % plane.local_addr[1]
        pools, events = {}, {}
        try:
            for name in ("a", "b"):
                ev = []
                events[name] = ev
                pools[name] = TpuSerfPool(
                    _fast_cfg(name),
                    on_event=lambda k, p, _ev=ev: _ev.append((k, p)),
                    plane_addr=addr)
                await pools[name].start()
            assert await _wait(lambda: len(pools["a"].members()) == 2)
            # the swarm never leaks into the serf-boundary view
            assert {n.name for n in pools["a"].members()} == {"a", "b"}
            # kill b: detection decided by the kernel with 242 members
            await pools.pop("b").stop()
            assert await _wait(lambda: any(
                k == EV_FAILED and n.name == "b"
                for k, n in events["a"]), timeout=30.0), \
                [k for k, _ in events["a"]]
        finally:
            for pool in pools.values():
                await pool.stop()
            await plane.stop()
    loop.run_until_complete(body())


@pytest.mark.slow
@pytest.mark.timeout_s(600)
def test_mixed_backend_cross_dc_federation(loop):
    """Federation across datacenters with MIXED membership substrates:
    dc1's LAN runs on the TPU plane (gossip_backend=tpu), dc2's on the
    asyncio backend.  The WAN pool is always asyncio (servers-only,
    tiny), so a kernel-backed DC federates with a classic one — the
    graft must not leak into the cross-DC topology.  Cross-DC KV
    forwarding and datacenter discovery must work both ways."""
    from consul_tpu.agent.agent import Agent, AgentConfig
    from consul_tpu.consensus.raft import RaftConfig

    FAST = RaftConfig(heartbeat_interval=0.03, election_timeout_min=0.06,
                      election_timeout_max=0.12, rpc_timeout=0.5)
    TIMING = dict(probe_interval=0.05, probe_timeout=0.02,
                  gossip_interval=0.02, suspicion_mult=3.0,
                  push_pull_interval=0.5, reap_interval=0.2)

    async def body():
        plane = GossipPlane(PlaneConfig(
            bind_port=0, capacity=16, slots=16, gossip_interval_s=0.02,
            suspicion_mult=1.0, hb_lapse_s=0.3))
        await plane.start()
        a1 = a2 = None
        try:
            a1 = Agent(AgentConfig(
                node_name="t1", datacenter="dc1", server=True,
                bootstrap=True, rpc_mesh_port=0, http_port=0, dns_port=0,
                serf_wan_port=0, serf_timing=dict(TIMING), raft_config=FAST,
                gossip_backend="tpu",
                gossip_plane="127.0.0.1:%d" % plane.local_addr[1]))
            await a1.start()
            a2 = Agent(AgentConfig(
                node_name="s1", datacenter="dc2", server=True,
                bootstrap=True, rpc_mesh_port=0, http_port=0, dns_port=0,
                serf_lan_port=0, serf_wan_port=0,
                serf_timing=dict(TIMING), raft_config=FAST))
            await a2.start()
            await a1.server.wait_for_leader()
            await a2.server.wait_for_leader()
            # WAN federation: dc1's server dials dc2's WAN pool.
            n = await a1.join(
                ["127.0.0.1:%d" % a2.wan_pool.local_addr[1]], wan=True)
            assert n >= 1
            assert await _wait(lambda: "dc2" in a1.server.known_datacenters()
                               and "dc1" in a2.server.known_datacenters())
            # cross-DC KV through the wire dispatch (the forward()
            # prologue lives in the RPC layer): write into dc2 THROUGH
            # the kernel-backed dc1 and read it back locally in dc2
            from consul_tpu.structs.structs import (KVSOp, KVSRequest,
                                                    KeyRequest)
            from consul_tpu.structs.structs import DirEntry
            out = await a1.server.rpc_server._dispatch({
                "Method": "KVS.Apply",
                "Body": KVSRequest(
                    datacenter="dc2", op=KVSOp.SET.value,
                    dir_ent=DirEntry(key="fed/x",
                                     value=b"from-dc1")).to_wire()})
            assert not out["Error"], out
            _, ents = await a2.server.kvs.get(KeyRequest(
                datacenter="dc2", key="fed/x"))
            assert ents and ents[0].value == b"from-dc1"
            # and the reverse direction writes dc1's store via dc2
            out = await a2.server.rpc_server._dispatch({
                "Method": "KVS.Apply",
                "Body": KVSRequest(
                    datacenter="dc1", op=KVSOp.SET.value,
                    dir_ent=DirEntry(key="fed/y",
                                     value=b"from-dc2")).to_wire()})
            assert not out["Error"], out
            _, ents = await a1.server.kvs.get(KeyRequest(
                datacenter="dc1", key="fed/y"))
            assert ents and ents[0].value == b"from-dc2"
        finally:
            for a in (a1, a2):
                if a is not None:
                    await a.stop()
            await plane.stop()
    loop.run_until_complete(body())


@pytest.mark.slow
@pytest.mark.timeout_s(300)
def test_plane_restart_resyncs_agents(loop):
    """The plane daemon dying is a control-plane outage, not a cluster
    death: agents keep running, redial the rendezvous, re-register, and
    the welcome snapshot resyncs their membership view."""
    async def body():
        cfg = PlaneConfig(bind_port=0, capacity=16, slots=16,
                          gossip_interval_s=0.02, suspicion_mult=1.0,
                          hb_lapse_s=0.3)
        plane = GossipPlane(cfg)
        await plane.start()
        port = plane.local_addr[1]
        addr = f"127.0.0.1:{port}"
        pools = {}
        try:
            for name in ("a", "b"):
                pools[name] = TpuSerfPool(_fast_cfg(name),
                                          plane_addr=addr)
                await pools[name].start()
            assert await _wait(lambda: len(pools["a"].members()) == 2)
            # plane goes down hard...
            await plane.stop()
            await asyncio.sleep(0.3)
            # ...and a fresh one comes up on the same rendezvous port
            cfg2 = PlaneConfig(bind_port=port, capacity=16, slots=16,
                               gossip_interval_s=0.02, suspicion_mult=1.0,
                               hb_lapse_s=0.3)
            plane = GossipPlane(cfg2)
            await plane.start()
            # both agents redial, re-register, and see each other again
            assert await _wait(
                lambda: {n.name for n in pools["a"].alive_members()}
                == {"a", "b"}
                and {n.name for n in pools["b"].alive_members()}
                == {"a", "b"}, timeout=30.0), \
                {n: [m.name for m in p.alive_members()]
                 for n, p in pools.items()}
        finally:
            for pool in pools.values():
                await pool.stop()
            await plane.stop()
    loop.run_until_complete(body())


@pytest.mark.slow
@pytest.mark.timeout_s(300)
def test_tpu_force_leave_reaps_failed_node(loop):
    """serf force-leave semantics on the plane: a FAILED node is moved
    to left (reaped) on request — and an alive node cannot be
    force-left (the op only acts on failed members, like
    RemoveFailedNode, consul/server.go:624-632)."""
    async def body():
        c = Cluster("tpu")
        try:
            await c.start(["a", "b", "c"])
            assert await _wait(
                lambda: len(c.pools["a"].alive_members()) == 3)
            # force-leave on an ALIVE node is a no-op
            assert c.pools["a"].force_leave("b")
            await asyncio.sleep(0.5)
            assert c.member_states("a").get("b") == STATE_ALIVE
            # kill c, wait for the kernel's dead verdict...
            await c.kill("c")
            assert await _wait(lambda: any(
                k == EV_FAILED and n.name == "c"
                for k, n in c.events["a"]), timeout=30.0)
            # ...then force-leave reaps it: EV_LEAVE + gone from members
            assert c.pools["a"].force_leave("c")
            assert await _wait(lambda: any(
                k == EV_LEAVE and n.name == "c"
                for k, n in c.events["a"])), \
                [k for k, _ in c.events["a"]]
            assert await _wait(
                lambda: "c" not in c.member_states("a"))
        finally:
            await c.stop()
    loop.run_until_complete(body())


@pytest.mark.slow
@pytest.mark.timeout_s(300)
def test_plane_keyring_auth(loop):
    """An armed plane keyring is enforced at registration: the agents'
    `encrypt` gossip key doubles as the plane admission secret
    (registration_proof), so gossip_backend=tpu cannot silently
    downgrade the encrypted-fabric posture to an open port."""
    import base64

    from consul_tpu.agent.keyring import Keyring

    key = base64.b64encode(b"0123456789abcdef").decode()
    wrong = base64.b64encode(b"fedcba9876543210").decode()

    async def body():
        plane = GossipPlane(PlaneConfig(
            bind_port=0, capacity=8, slots=8, gossip_interval_s=0.02,
            suspicion_mult=1.0, hb_lapse_s=0.3, encrypt_keys=[key]))
        await plane.start()
        addr = "127.0.0.1:%d" % plane.local_addr[1]
        try:
            # no keyring -> refused with the auth error
            bare = TpuSerfPool(_fast_cfg("bare"), plane_addr=addr,
                               use_native=False)
            with pytest.raises(ConnectionError, match="authentication"):
                await bare._connect(addr)
            # wrong key -> refused
            liar = TpuSerfPool(_fast_cfg("liar"),
                               keyring=Keyring(initial_key=wrong),
                               plane_addr=addr, use_native=False)
            with pytest.raises(ConnectionError, match="authentication"):
                await liar._connect(addr)
            assert not plane._nodes_by_name
            # matching keyring -> admitted (native default transport)
            ev = []
            good = TpuSerfPool(_fast_cfg("good"),
                               keyring=Keyring(initial_key=key),
                               on_event=lambda k, p: ev.append((k, p)),
                               plane_addr=addr)
            try:
                await good.start()
                assert await _wait(lambda: any(
                    k == EV_JOIN and n.name == "good" for k, n in ev))
            finally:
                await good.stop()
            # rotation: proof with a non-primary installed key passes
            ring2 = Keyring(initial_key=key)
            ring2.install(wrong)
            ring2.use(wrong)  # wrong becomes primary locally
            plane.config.encrypt_keys = [key, wrong]
            alt = TpuSerfPool(_fast_cfg("alt"), keyring=ring2,
                              plane_addr=addr, use_native=False)
            try:
                await alt._connect(addr)
                assert "alt" in plane._nodes_by_name
            finally:
                await alt.stop()
        finally:
            await plane.stop()
    loop.run_until_complete(body())


def test_plane_auth_replay_window():
    """A stale or skewed registration proof is refused (bounded replay
    window) and a valid-window proof verifies."""
    import base64
    import time as _time

    from consul_tpu.gossip.plane import registration_proof

    key = base64.b64encode(b"0123456789abcdef").decode()
    plane = GossipPlane(PlaneConfig(encrypt_keys=[key], auth_skew_s=30.0))

    def reg(ts, nonce, tags=None):
        return {"name": "n1", "addr": "127.0.0.1", "port": 7,
                "tags": dict(tags or {}),
                "auth_ts": ts, "auth_nonce": nonce,
                "auth": registration_proof(key, "n1", "127.0.0.1", 7,
                                           ts, nonce, tags)}

    now = int(_time.time())
    assert plane._verify_auth(reg(now, b"\x01" * 8))
    # replay of the SAME captured frame is refused (nonce is single-use)
    assert not plane._verify_auth(reg(now, b"\x01" * 8))
    assert not plane._verify_auth(reg(now - 3600, b"\x02" * 8))
    assert not plane._verify_auth(reg(now + 3600, b"\x03" * 8))
    # tampered fields invalidate the proof — including tags, which the
    # MAC covers (role/dc routing must not be forgeable)
    m = reg(now, b"\x04" * 8)
    m["port"] = 8
    assert not plane._verify_auth(m)
    m = reg(now, b"\x05" * 8, tags={"role": "node"})
    m["tags"] = {"role": "consul"}
    assert not plane._verify_auth(m)
    # no keys on the wire at all
    assert not plane._verify_auth({"name": "n1", "addr": "", "port": 0})
    # malformed auth fields are a refusal, never a handler crash
    assert not plane._verify_auth({"name": "n1", "auth_ts": "abc",
                                   "auth": "str-not-bytes",
                                   "auth_nonce": 3})


def test_plane_left_tombstone_reap():
    """Left names are reaped after the tombstone window — node-name
    churn must not grow the member list without bound (serf reap)."""
    plane = GossipPlane(PlaneConfig(capacity=4, tombstone_timeout_s=0.05))
    import time as _time

    from consul_tpu.gossip.plane import PlaneNode
    now = _time.monotonic()
    plane._nodes_by_name = {
        "gone": PlaneNode(id=-1, name="gone", status="left",
                          left_at=now - 1.0),
        "fresh": PlaneNode(id=-1, name="fresh", status="left",
                           left_at=now),
        "live": PlaneNode(id=0, name="live", status="alive"),
    }
    plane._reap_tombstones()
    assert set(plane._nodes_by_name) == {"fresh", "live"}


@pytest.mark.slow
@pytest.mark.timeout_s(300)
def test_events_ride_dissemination_kernel(loop):
    """User events are kernel dynamics, not host fanout: a fired event
    enters the [E, N] flood (lamport-stamped on-device), real agents are
    notified when THEIR node id has seen it in the kernel arrays, and
    the sim swarm shares the same flood (coverage observable includes
    it).  Reference: EventFire -> serf UserEvent -> gossip broadcast
    (consul/internal_endpoint.go:87)."""
    async def body():
        plane = GossipPlane(PlaneConfig(
            bind_port=0, capacity=16, slots=16, sim_nodes=512,
            gossip_interval_s=0.02, suspicion_mult=1.0, hb_lapse_s=0.5))
        await plane.start()
        addr = "127.0.0.1:%d" % plane.local_addr[1]
        pools, events = {}, {}
        try:
            for name in ("a", "b", "c"):
                ev = []
                events[name] = ev
                pools[name] = TpuSerfPool(
                    _fast_cfg(name),
                    on_event=lambda k, p, _ev=ev: _ev.append((k, p)),
                    plane_addr=addr)
                await pools[name].start()
            assert await _wait(
                lambda: len(pools["a"].alive_members()) == 3)
            pools["a"].user_event("deploy", b"v7")

            def got(name):
                return [p for k, p in events[name]
                        if k == "user" and p.get("name") == "deploy"]
            assert await _wait(lambda: got("a") and got("b") and got("c"))
            # one lamport time, assigned by the kernel, seen by everyone
            lts = {got(n)[0]["ltime"] for n in ("a", "b", "c")}
            assert len(lts) == 1 and lts.pop() >= 1
            # the sim swarm shares the flood: coverage approaches 1.0
            # across the 528-node universe while the slot lives
            assert await _wait(
                lambda: any(v >= 0.95 for v in plane.event_coverage().values())
                or not plane.event_coverage(), timeout=10.0)
            # a second event gets a LATER lamport time
            pools["b"].user_event("deploy2", b"v8")
            assert await _wait(lambda: any(
                k == "user" and p.get("name") == "deploy2"
                for k, p in events["c"]))
            lt2 = [p for k, p in events["c"]
                   if k == "user" and p.get("name") == "deploy2"][0]["ltime"]
            assert lt2 > [p for k, p in events["c"]
                          if k == "user" and p.get("name") == "deploy"][0]["ltime"]
        finally:
            for pool in pools.values():
                await pool.stop()
            await plane.stop()
    loop.run_until_complete(body())


@pytest.mark.slow
@pytest.mark.timeout_s(600)
def test_plane_soak_many_agents_large_sim():
    """The hybrid BASELINE posture at test scale: 64 live agents + a
    100k-node sim swarm in one kernel session, sustaining the round
    cadence while events fire and an agent dies and rejoins.  Gates:
    the plane keeps >= 40% of the configured round rate end-to-end (a
    frozen/starved plane fails this hard), every agent sees the event
    and the kill, and the rejoin lands."""
    import time as _time

    async def body():
        # 0.1s rounds: the CPU kernel's 100k-node dispatch is ~80ms for
        # 4 rounds (on-chip it is ~ms) — the cadence gate asserts the
        # plane's SCHEDULING holds up under 64 agents + events + churn,
        # not that one CI core outruns a TPU.
        interval = 0.1
        plane = GossipPlane(PlaneConfig(
            bind_port=0, capacity=128, slots=64, sim_nodes=100_000,
            gossip_interval_s=interval, suspicion_mult=1.0,
            hb_lapse_s=1.0))
        await plane.start()
        addr = "127.0.0.1:%d" % plane.local_addr[1]
        pools, events = {}, {}
        try:
            t0 = _time.monotonic()
            for k in range(64):
                name = f"n{k:02d}"
                ev = []
                events[name] = ev
                pools[name] = TpuSerfPool(
                    _fast_cfg(name),
                    on_event=lambda kk, p, _ev=ev: _ev.append((kk, p)),
                    plane_addr=addr, use_native=False)
                await pools[name].start()
            # every agent converges on the full member view
            assert await _wait(
                lambda: all(len(p.alive_members()) == 64
                            for p in pools.values()), timeout=60.0), \
                sorted(len(p.alive_members()) for p in pools.values())[:5]
            # an event fired at one agent reaches all the others
            pools["n00"].user_event("soak", b"x")
            assert await _wait(
                lambda: all(any(kk == "user" and p.get("name") == "soak"
                                for kk, p in ev) for ev in events.values()),
                timeout=30.0)
            # kill one agent; everyone else gets the kernel's verdict
            await pools["n13"].stop()
            assert await _wait(
                lambda: all(any(kk == EV_FAILED and n.name == "n13"
                                for kk, n in events[other])
                            for other in events if other != "n13"),
                timeout=90.0)
            # it rejoins (new pool, same name)
            ev13 = events["n13"] = []
            pools["n13"] = TpuSerfPool(
                _fast_cfg("n13"),
                on_event=lambda kk, p, _ev=ev13: _ev.append((kk, p)),
                plane_addr=addr, use_native=False)
            await pools["n13"].start()
            assert await _wait(lambda: any(
                kk == EV_JOIN and n.name == "n13"
                for kk, n in events["n00"][::-1]), timeout=30.0)
            # cadence: the plane kept dispatching throughout (a frozen
            # or heartbeat-starved plane stalls at a handful of rounds)
            # and is still advancing now.  No wall-clock ratio gate:
            # this one CI core also runs all 64 agents and any
            # concurrent load, and the ticker's bounded catch-up
            # deliberately trades rate for liveness under contention.
            assert plane._rounds_done >= 80, plane._rounds_done
            r0 = plane._rounds_done
            await asyncio.sleep(interval * 4 * 4)
            assert plane._rounds_done > r0
            # the sim swarm stayed healthy: no mass false verdicts
            assert int(plane._state.n_false_dead) == 0
        finally:
            for pool in pools.values():
                try:
                    await pool.stop()
                except Exception:
                    pass
            await plane.stop()
    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(body())
    finally:
        loop.close()


@pytest.mark.slow
@pytest.mark.timeout_s(600)
def test_kernel_backed_cross_dc_federation(loop):
    """BOTH datacenters on gossip_backend=tpu: two planes = two DCs
    (each DC one kernel session — the reference's two-pool topology,
    consul/server.go:266-273), WAN pool bridging the servers.  Cross-DC
    KV forwarding, datacenter discovery, and cross-DC EVENT fire
    (EventFireRequest.Datacenter, event_endpoint.go:33-40) must all
    work through two kernel-backed membership substrates."""
    from consul_tpu.agent.agent import Agent, AgentConfig
    from consul_tpu.consensus.raft import RaftConfig

    FAST = RaftConfig(heartbeat_interval=0.03, election_timeout_min=0.06,
                      election_timeout_max=0.12, rpc_timeout=0.5)
    TIMING = dict(probe_interval=0.05, probe_timeout=0.02,
                  gossip_interval=0.02, suspicion_mult=3.0,
                  push_pull_interval=0.5, reap_interval=0.2)

    async def body():
        planes = []
        for _ in range(2):
            pl = GossipPlane(PlaneConfig(
                bind_port=0, capacity=16, slots=16, gossip_interval_s=0.02,
                suspicion_mult=1.0, hb_lapse_s=0.3))
            await pl.start()
            planes.append(pl)
        a1 = a2 = None
        try:
            a1 = Agent(AgentConfig(
                node_name="t1", datacenter="dc1", server=True,
                bootstrap=True, rpc_mesh_port=0, http_port=0, dns_port=0,
                serf_wan_port=0, serf_timing=dict(TIMING), raft_config=FAST,
                gossip_backend="tpu",
                gossip_plane="127.0.0.1:%d" % planes[0].local_addr[1]))
            await a1.start()
            a2 = Agent(AgentConfig(
                node_name="t2", datacenter="dc2", server=True,
                bootstrap=True, rpc_mesh_port=0, http_port=0, dns_port=0,
                serf_wan_port=0, serf_timing=dict(TIMING), raft_config=FAST,
                gossip_backend="tpu",
                gossip_plane="127.0.0.1:%d" % planes[1].local_addr[1]))
            await a2.start()
            await a1.server.wait_for_leader()
            await a2.server.wait_for_leader()
            n = await a1.join(
                ["127.0.0.1:%d" % a2.wan_pool.local_addr[1]], wan=True)
            assert n >= 1
            assert await _wait(lambda: "dc2" in a1.server.known_datacenters()
                               and "dc1" in a2.server.known_datacenters())
            # cross-DC KV both ways through two kernel-backed substrates
            from consul_tpu.structs.structs import (DirEntry, KVSOp,
                                                    KVSRequest, KeyRequest)
            out = await a1.server.rpc_server._dispatch({
                "Method": "KVS.Apply",
                "Body": KVSRequest(
                    datacenter="dc2", op=KVSOp.SET.value,
                    dir_ent=DirEntry(key="fed/x",
                                     value=b"from-dc1")).to_wire()})
            assert not out["Error"], out
            _, ents = await a2.server.kvs.get(KeyRequest(
                datacenter="dc2", key="fed/x"))
            assert ents and ents[0].value == b"from-dc1"
            out = await a2.server.rpc_server._dispatch({
                "Method": "KVS.Apply",
                "Body": KVSRequest(
                    datacenter="dc1", op=KVSOp.SET.value,
                    dir_ent=DirEntry(key="fed/y",
                                     value=b"from-dc2")).to_wire()})
            assert not out["Error"], out
            _, ents = await a1.server.kvs.get(KeyRequest(
                datacenter="dc1", key="fed/y"))
            assert ents and ents[0].value == b"from-dc2"
            # cross-DC event: fired at dc1 NAMING dc2 -> floods dc2's
            # kernel plane, lands in dc2's event ring (and not dc1's)
            from consul_tpu.structs.structs import UserEvent
            await a1.events.fire(UserEvent(name="xdc-deploy",
                                           payload=b"v9",
                                           datacenter="dc2"))
            assert await _wait(lambda: any(
                e.name == "xdc-deploy" and e.payload == b"v9"
                for e in a2.events.events()), timeout=20.0), \
                [e.name for e in a2.events.events()]
            assert not any(e.name == "xdc-deploy"
                           for e in a1.events.events())
        finally:
            for a in (a1, a2):
                if a is not None:
                    await a.stop()
            for pl in planes:
                await pl.stop()
    loop.run_until_complete(body())


@pytest.mark.slow
@pytest.mark.timeout_s(300)
def test_duplicate_leave_is_harmless(loop):
    """A second leave frame for an already-left node must not corrupt
    the highest id's lifecycle entries (the -1 index regression)."""
    async def body():
        plane = GossipPlane(PlaneConfig(
            bind_port=0, capacity=8, slots=8, gossip_interval_s=0.02,
            suspicion_mult=1.0, hb_lapse_s=0.3))
        await plane.start()
        addr = "127.0.0.1:%d" % plane.local_addr[1]
        a = TpuSerfPool(_fast_cfg("a"), plane_addr=addr, use_native=False)
        b = TpuSerfPool(_fast_cfg("b"), plane_addr=addr, use_native=False)
        try:
            await a.start()
            await b.start()
            assert await _wait(lambda: len(a.alive_members()) == 2)
            eligible_before = plane._eligible.copy()
            await b.leave()
            await b.leave()  # duplicate
            await asyncio.sleep(0.3)
            # a's slot is untouched; only b's went ineligible
            aid = plane._nodes_by_name["a"].id
            assert plane._eligible[aid]
            assert plane._nodes_by_name["b"].id == -1
            # the top id's entries were not clobbered by a -1 write
            assert plane._join[-1] == plane._join[5]  # both untouched ids
        finally:
            await a.stop()
            await b.stop()
            await plane.stop()
    loop.run_until_complete(body())


@pytest.mark.slow
@pytest.mark.timeout_s(300)
def test_plane_stats_op(loop):
    """The plane's serf.Stats() role: a registered agent can query the
    kernel session's counters (unregistered connections get nothing —
    an armed keyring gates observability too)."""
    async def body():
        c = Cluster("tpu")
        try:
            await c.start(["a", "b"])
            assert await _wait(
                lambda: len(c.pools["a"].alive_members()) == 2)
            st = await c.pools["a"].plane_stats()
            assert st.get("round", -1) >= 0
            assert st["members"]["alive"] + st["members"]["joining"] == 2
            assert st["capacity"] == 32
            assert st["kernel"]["n_false_dead"] == 0
            # kill b; after the verdict the stats reflect it
            await c.kill("b")
            assert await _wait(lambda: any(
                k == EV_FAILED and n.name == "b"
                for k, n in c.events["a"]), timeout=30.0)
            st = await c.pools["a"].plane_stats()
            assert st["members"]["failed"] == 1
            assert st["kernel"]["n_detected"] >= 1
        finally:
            await c.stop()
    loop.run_until_complete(body())


@pytest.mark.slow
@pytest.mark.timeout_s(300)
def test_ghost_registration_reaped(loop):
    """A node that dies mid-join (registered, heartbeats lapsed before
    the kernel ever admitted it) was never announced to anyone — it
    must cease entirely: id released, no ghost in welcome snapshots."""
    import time as _time

    async def body():
        plane = GossipPlane(PlaneConfig(
            bind_port=0, capacity=8, slots=8, gossip_interval_s=0.02,
            suspicion_mult=1.0, hb_lapse_s=0.2))
        await plane.start()
        try:
            class _W:
                def write(self, b):
                    pass

                def close(self):
                    pass

            node, err = plane._register(
                {"name": "ghost", "addr": "", "port": 0, "tags": {}}, _W())
            assert node is not None, err
            gid = node.id
            free_before = len(plane._free_ids)
            # died instantly: failing since round 0, last hb long ago
            plane._fail[gid] = 0
            plane._hb_at[gid] = _time.monotonic() - 100
            # ghost window is max(10*hb_lapse, 5s)
            assert await _wait(
                lambda: "ghost" not in plane._nodes_by_name, timeout=12.0)
            assert gid not in plane._nodes_by_id
            assert len(plane._free_ids) == free_before + 1
            assert not any(m["name"] == "ghost"
                           for m in plane.members_wire())
        finally:
            await plane.stop()
    loop.run_until_complete(body())
