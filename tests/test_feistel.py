"""Permutation op: bijectivity, invertibility, uniformity smoke checks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consul_tpu.ops.feistel import feistel_permute, feistel_inverse, random_targets


@pytest.mark.parametrize("n", [4, 97, 1024, 1000, 4096, 12345])
def test_bijection_and_inverse(n):
    key = jax.random.key(7)
    x = jnp.arange(n, dtype=jnp.uint32)
    y = feistel_permute(x, key, n)
    assert len(np.unique(np.asarray(y))) == n
    assert int(jnp.max(y)) < n
    back = feistel_inverse(y, key, n)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))


def test_different_keys_differ():
    n = 1024
    x = jnp.arange(n, dtype=jnp.uint32)
    y1 = feistel_permute(x, jax.random.key(1), n)
    y2 = feistel_permute(x, jax.random.key(2), n)
    assert np.asarray(y1 != y2).mean() > 0.9


def test_permutation_is_mixing():
    # A fixed point or near-identity permutation would break gossip.
    n = 4096
    x = jnp.arange(n, dtype=jnp.uint32)
    y = np.asarray(feistel_permute(x, jax.random.key(3), n))
    assert (y == np.arange(n)).mean() < 0.01
    # displacement roughly uniform: mean |y - x| ~ n/3 for random perm
    disp = np.abs(y.astype(np.int64) - np.arange(n)).mean()
    assert n / 5 < disp < n / 2


def test_random_targets_excludes_self():
    key = jax.random.key(0)
    t = np.asarray(random_targets(key, 50, (50,)))
    assert (t == np.arange(50)).sum() == 0
    assert t.min() >= 0 and t.max() < 50


def test_random_targets_2d():
    key = jax.random.key(0)
    t = np.asarray(random_targets(key, 33, (33, 3)))
    assert (t == np.arange(33)[:, None]).sum() == 0
    assert t.min() >= 0 and t.max() < 33
