"""UI test tier: the role of the reference's QUnit suite (``ui/tests/``).

No JS runtime ships in this image, so instead of executing app.js we
test the two contracts that actually break SPAs in practice:

1. **Data contract** — every ``/v1/...`` endpoint app.js fetches must
   exist on a live agent and return the JSON shape the UI destructures
   (field names are asserted, since a renamed field fails silently in
   the browser).  This is what most of the reference's QUnit tests
   cover via its Ember models.
2. **Routing/asset contract** — the hash routes the router implements,
   the nav links in index.html, and the assets it references must
   agree and be served under ``/ui/``.

Endpoints are EXTRACTED from app.js (regex over fetch paths), so adding
a fetch to the UI without server support fails here.
"""

import asyncio
import re

import httpx
import pytest

from consul_tpu.agent.agent import AgentConfig
from test_agent_http import AgentHarness

UI_DIR = "consul_tpu/ui"


def _read(name: str) -> str:
    import os
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(here, UI_DIR, name)) as f:
        return f.read()


@pytest.fixture(scope="module")
def agent_http():
    """Live agent (own thread + loop) + its HTTP base url, pre-seeded
    with the service the UI screens browse."""
    h = AgentHarness(AgentConfig(node_name="ui-test")).start()

    async def seed():
        from consul_tpu.structs.structs import NodeService
        await h.agent.add_service(NodeService(
            id="web1", service="web", port=8080, tags=["ui"]))
    asyncio.run_coroutine_threadsafe(seed(), h.loop).result(10)
    yield h.agent, h.http_addr
    h.stop()


def _get(base: str, path: str):
    r = httpx.get(base + path, timeout=10)
    return r.status_code, (r.json() if r.content else None), \
        r.headers.get("Content-Type", "")


class TestUIDataContract:
    def test_all_fetched_endpoints_are_served(self, agent_http):
        """Every endpoint pattern app.js fetches answers 200 with JSON."""
        agent, base = agent_http
        # seed KV through the same PUT path the UI's editor uses
        assert httpx.put(base + "/v1/kv/app/config", content=b"x=1",
                         timeout=10).status_code == 200

        app_js = _read("app.js")
        # Concrete instantiations of every fetch pattern in app.js
        # (all three JS quote styles, or the guarantee is hollow).
        fetched = set(re.findall(r'["\'`](/v1/[^"\'`?]*)', app_js))
        concrete = {
            "/v1/internal/ui/services": "/v1/internal/ui/services",
            "/v1/health/service/${encodeURIComponent(name)}":
                "/v1/health/service/web",
            "/v1/internal/ui/nodes": "/v1/internal/ui/nodes",
            "/v1/internal/ui/node/${encodeURIComponent(name)}":
                "/v1/internal/ui/node/ui-test",
            "/v1/kv/${kvPath(k)}": "/v1/kv/app/config",
            "/v1/kv/${kvPath(key)}": "/v1/kv/app/config",
            "/v1/kv/${kvPath(prefix)}": "/v1/kv/app/config",
            "/v1/agent/self": "/v1/agent/self",
        }
        unmapped = fetched - set(concrete)
        assert not unmapped, f"app.js fetches unmapped endpoints: {unmapped}"
        for pattern, path in concrete.items():
            status, body, ctype = _get(base, path)
            assert status == 200, (pattern, path, status)
            assert "json" in ctype, (pattern, path, ctype)
        # the keys-listing variant the KV browser uses
        status, keys, _ = _get(base, "/v1/kv/app/?keys&separator=/")
        assert status == 200 and keys == ["app/config"]

    def test_fields_the_ui_destructures(self, agent_http):
        """Field names app.js reads must exist in the payloads."""
        agent, base = agent_http
        _, services, _ = _get(base, "/v1/internal/ui/services")
        assert services and {"Name", "Nodes", "ChecksPassing",
                             "ChecksWarning", "ChecksCritical"} <= set(
            services[0])
        _, insts, _ = _get(base, "/v1/health/service/web")
        assert insts and {"Node", "Service", "Checks"} <= set(insts[0])
        assert {"Node", "Address"} <= set(insts[0]["Node"])
        assert {"Service", "Port", "Tags"} <= set(insts[0]["Service"])
        _, nodes, _ = _get(base, "/v1/internal/ui/nodes")
        assert nodes and {"Node", "Address", "Services",
                          "Checks"} <= set(nodes[0])
        _, node, _ = _get(base, "/v1/internal/ui/node/ui-test")
        assert {"Node", "Services"} <= set(node)
        _, me, _ = _get(base, "/v1/agent/self")
        assert "Config" in me and "NodeName" in me["Config"]


class TestUIRoutingContract:
    def test_nav_links_match_router_routes(self):
        app_js = _read("app.js")
        index = _read("index.html")
        nav_routes = set(re.findall(r'href="(#/[a-z]+)"', index))
        assert nav_routes == {"#/services", "#/nodes", "#/kv"}
        # Every nav route must have a branch in route()'s dispatch map
        # (the `name: () =>` entries) — matching the actual dispatch
        # code, not the route-table comment at the top of the file.
        router = re.search(r"function route\(\).*?^\}", app_js,
                           re.S | re.M)
        assert router, "app.js lost its route() dispatcher"
        dispatch = set(re.findall(r"^\s*([a-z]+):\s*\(\)\s*=>",
                                  router.group(0), re.M))
        assert {r[2:] for r in nav_routes} <= dispatch, \
            (nav_routes, dispatch)

    def test_assets_served_under_ui(self, agent_http):
        _, base = agent_http
        for asset, must_contain in (("/ui/", "<script src=\"app.js\">"),
                                    ("/ui/app.js", "route()"),
                                    ("/ui/style.css", "body")):
            r = httpx.get(base + asset, timeout=10)
            assert r.status_code == 200 and must_contain in r.text, asset
