"""Black-box tier: real forked agents driven over HTTP/DNS/IPC.

Parity target: the reference's ``api/*_test.go`` + ``testutil``
fork/exec tier (testutil/server.go:85-142) — nothing here touches
in-process objects; every assertion goes through a public wire surface
of a subprocess running the real CLI daemon.
"""

import base64
import time

import pytest

from blackbox_util import TestServer


@pytest.fixture(scope="module")
def server():
    s = TestServer("bb-single").start()
    try:
        s.wait_for_api()
        s.wait_for_leader()
    except Exception:
        print(s.output())
        s.stop()
        raise
    yield s
    s.stop()


class TestSingleAgentBlackBox:
    def test_self_and_leader(self, server):
        me = server.http_get("/v1/agent/self")
        assert me["Config"]["NodeName"] == "bb-single"
        assert server.http_get("/v1/status/leader") == "bb-single"

    def test_kv_roundtrip(self, server):
        assert server.http_put("/v1/kv/bb/key", b"hello") is True
        got = server.http_get("/v1/kv/bb/key")
        assert base64.b64decode(got[0]["Value"]) == b"hello"
        assert server.http_delete("/v1/kv/bb/key") is True

    def test_service_and_dns(self, server):
        server.http_put("/v1/agent/service/register",
                        {"Name": "web", "Port": 8080})
        # anti-entropy pushes it to the catalog; poll the public surface
        deadline = time.monotonic() + 15
        nodes = []
        while time.monotonic() < deadline:
            nodes = server.http_get("/v1/catalog/service/web")
            if nodes:
                break
            time.sleep(0.2)
        assert nodes and nodes[0]["Node"] == "bb-single"
        r = server.dns_query("web.service.consul", qtype=33)  # SRV
        assert r["rcode"] == 0 and r["ancount"] == 1

    def test_cli_members_over_ipc(self, server):
        out = server.cli("members")
        assert out.returncode == 0, out.stderr
        assert "bb-single" in out.stdout
        assert "alive" in out.stdout

    def test_cli_info_over_ipc(self, server):
        out = server.cli("info")
        assert out.returncode == 0, out.stderr
        assert "raft" in out.stdout

    def test_web_ui_served(self, server):
        """The bundled UI ships at /ui/ (http.go:267-270 role)."""
        import urllib.request
        base = f"http://127.0.0.1:{server.ports['http']}"
        with urllib.request.urlopen(f"{base}/ui/", timeout=10) as r:
            html = r.read().decode()
        assert "<html" in html and "app.js" in html
        with urllib.request.urlopen(f"{base}/ui/app.js", timeout=10) as r:
            js = r.read().decode()
        assert "/v1/internal/ui/services" in js
        # /ui redirects to /ui/
        req = urllib.request.Request(f"{base}/ui")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.url.endswith("/ui/")

    def test_metrics_endpoint(self, server):
        snap = server.http_get("/v1/agent/metrics")
        merged = {}
        for iv in snap:
            merged.update(iv["Counters"])
            merged.update(iv["Samples"])
        assert merged, "no metrics recorded"


class TestClientAgentBlackBox:
    def test_forked_client_stays_client(self):
        """A config with server=false must NOT be promoted to a
        bootstrap server by the CLI's dev-mode default (regression: the
        client agent elected itself leader)."""
        srv = TestServer("bbc-s1").start()
        cli = None
        try:
            srv.wait_for_api()
            srv.wait_for_leader()
            cli = TestServer("bbc-c1", server=False, bootstrap=False,
                             retry_join=[srv.lan_addr]).start()
            cli.wait_for_api()
            me = cli.http_get("/v1/agent/self")
            assert me["Config"]["Server"] is False, me["Config"]
            # its leader is the REAL server, not itself
            assert cli.wait_for_leader(30) == "bbc-s1"
            # KV via the client lands on the server
            assert cli.http_put("/v1/kv/via-client", b"x") is True
            got = srv.http_get("/v1/kv/via-client")
            assert got and got[0]["Key"] == "via-client"
        except Exception:
            print(srv.output()[-1500:])
            if cli:
                print(cli.output()[-1500:])
            raise
        finally:
            if cli:
                cli.stop()
            srv.stop()


class TestClusterBlackBox:
    def test_three_forked_servers_form_a_cluster(self):
        """BASELINE config #1 shape, fully black-box: three real agent
        processes join over loopback gossip, elect one leader, replicate
        a KV write, and report full membership over the CLI."""
        s1 = TestServer("bb-c1", bootstrap=False, bootstrap_expect=3).start()
        servers = [s1]
        try:
            s1.wait_for_api()
            for name in ("bb-c2", "bb-c3"):
                s = TestServer(name, bootstrap=False, bootstrap_expect=3,
                               retry_join=[s1.lan_addr]).start()
                servers.append(s)
                s.wait_for_api()
            for s in servers:
                s.wait_for_leader(60)
            # one leader, agreed on by everyone
            leaders = {s.http_get("/v1/status/leader") for s in servers}
            assert len(leaders) == 1
            # members parity over the CLI (consul members output shape)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                out = servers[0].cli("members")
                if all(n in out.stdout
                       for n in ("bb-c1", "bb-c2", "bb-c3")):
                    break
                time.sleep(0.3)
            assert all(n in out.stdout for n in ("bb-c1", "bb-c2", "bb-c3")), \
                out.stdout
            # a write via one agent is readable via another
            assert servers[1].http_put("/v1/kv/cluster/x", b"42") is True
            deadline = time.monotonic() + 15
            got = None
            while time.monotonic() < deadline:
                try:
                    got = servers[2].http_get("/v1/kv/cluster/x")
                    if got:
                        break
                except Exception:
                    pass
                time.sleep(0.2)
            assert got and base64.b64decode(got[0]["Value"]) == b"42"
        except Exception:
            for s in servers:
                print(f"--- {s.name} ---")
                print(s.output()[-2000:])
            raise
        finally:
            for s in servers:
                s.stop()


def _make_ip_certs(tmp_path):
    """Self-signed CA + server cert valid for 127.0.0.1 (the HTTPS
    listener's bind address), via openssl."""
    import subprocess
    ca_key = tmp_path / "ca.key"
    ca_crt = tmp_path / "ca.crt"
    sv_key = tmp_path / "sv.key"
    sv_csr = tmp_path / "sv.csr"
    sv_crt = tmp_path / "sv.crt"
    ext = tmp_path / "ext.cnf"
    ext.write_text("subjectAltName=IP:127.0.0.1,DNS:localhost\n")
    cmds = [
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "1",
         "-subj", "/CN=ConsulTestCA"],
        ["openssl", "req", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(sv_key), "-out", str(sv_csr),
         "-subj", "/CN=127.0.0.1"],
        ["openssl", "x509", "-req", "-in", str(sv_csr), "-CA", str(ca_crt),
         "-CAkey", str(ca_key), "-CAcreateserial", "-out", str(sv_crt),
         "-days", "1", "-extfile", str(ext)],
    ]
    for cmd in cmds:
        proc = subprocess.run(cmd, capture_output=True)
        if proc.returncode != 0:
            pytest.skip(f"openssl unavailable/failed: {proc.stderr[:200]}")
    return str(ca_crt), str(sv_crt), str(sv_key)


class TestListenersBlackBox:
    """HTTPS + unix-socket listeners (command/agent/http.go:44-173,
    config.go UnixSockets): the same API served over every configured
    transport of a REAL forked agent."""

    def test_kv_over_https(self, tmp_path):
        import json as _json

        from consul_tpu.api.client import Client, Config

        ca, crt, key = _make_ip_certs(tmp_path)
        s = TestServer("bb-https")
        https_port = s.ports["server"] + 1  # +6 in the instance block
        with open(s.config_path) as f:
            cfg = _json.load(f)
        cfg["ports"]["https"] = https_port
        cfg["cert_file"] = crt
        cfg["key_file"] = key
        with open(s.config_path, "w") as f:
            _json.dump(cfg, f)
        s.start()
        try:
            s.wait_for_api()
            s.wait_for_leader()
            with Client(Config(address=f"127.0.0.1:{https_port}",
                               scheme="https", ca_file=ca)) as c:
                from consul_tpu.api.client import KVPair
                assert c.kv.put(KVPair(key="tls/key", value=b"secure"))
                pair, _ = c.kv.get("tls/key")
                assert pair is not None and pair.value == b"secure"
                # Plain HTTP on the same port must NOT work.
                import httpx
                with pytest.raises(Exception):
                    httpx.get(f"http://127.0.0.1:{https_port}/v1/status/leader",
                              timeout=3).raise_for_status()
        except Exception:
            print(s.output()[-2000:])
            raise
        finally:
            s.stop()

    def test_kv_and_ipc_over_unix_sockets(self, tmp_path):
        from consul_tpu.api.client import Client, Config, KVPair
        from consul_tpu.ipc import IPCClient

        http_sock = str(tmp_path / "http.sock")
        ipc_sock = str(tmp_path / "ipc.sock")
        s = TestServer("bb-unix", config_extra={
            "addresses": {"http": f"unix://{http_sock}",
                          "rpc": f"unix://{ipc_sock}"}})
        s.start()
        try:
            with Client(Config(address=f"unix://{http_sock}")) as c:
                deadline = time.monotonic() + 30
                leader = ""
                while time.monotonic() < deadline:
                    try:
                        leader = c.status.leader()
                        if leader:
                            break
                    except Exception:
                        pass
                    time.sleep(0.3)
                assert leader == "bb-unix", s.output()[-2000:]
                assert c.kv.put(KVPair(key="unix/key", value=b"sock"))
                pair, _ = c.kv.get("unix/key")
                assert pair is not None and pair.value == b"sock"
            with IPCClient(f"unix://{ipc_sock}") as ic:
                members = ic.members_lan()
                assert [m["Name"] for m in members] == ["bb-unix"]
        except Exception:
            print(s.output()[-2000:])
            raise
        finally:
            s.stop()


class TestTpuBackendBlackBox:
    """The graft, end to end: a forked gossip plane daemon + three real
    forked agents with gossip_backend=tpu.  Membership (join, members
    output, kill -> serfHealth critical) is decided by the SWIM kernel
    in the plane; the agents' HTTP/IPC surfaces must be
    indistinguishable from the asyncio backend."""

    def test_three_agents_kernel_membership(self):
        from blackbox_util import TestPlane

        plane = TestPlane().start()
        servers = []
        try:
            plane.wait_ready()
            names = ("bb-t1", "bb-t2", "bb-t3")
            servers = [TestServer(
                n, bootstrap=False, bootstrap_expect=3,
                config_extra={"gossip_backend": "tpu",
                              "gossip_plane": plane.addr}).start()
                for n in names]
            for s in servers:
                s.wait_for_api(60)
            for s in servers:
                s.wait_for_leader(90)
            # `consul members` over IPC: same output contract as the
            # asyncio backend (name + alive + role/dc tags).
            deadline = time.monotonic() + 30
            out = None
            while time.monotonic() < deadline:
                out = servers[0].cli("members")
                if all(n in out.stdout for n in names):
                    break
                time.sleep(0.3)
            assert all(n in out.stdout for n in names), out.stdout
            assert "alive" in out.stdout, out.stdout
            # `consul info` surfaces the plane's kernel counters
            info = servers[0].cli("info")
            assert "gossip_plane" in info.stdout, info.stdout
            assert "backend = tpu" in info.stdout, info.stdout
            # the catalog converged through reconcile: all 3 nodes
            nodes = servers[0].http_get("/v1/catalog/nodes")
            got = {n["Node"] for n in nodes}
            assert set(names) <= got, got
            # writes replicate across the quorum
            assert servers[1].http_put("/v1/kv/tpu/x", b"99") is True
            deadline = time.monotonic() + 15
            val = None
            while time.monotonic() < deadline:
                try:
                    val = servers[2].http_get("/v1/kv/tpu/x")
                    if val:
                        break
                except Exception:
                    pass
                time.sleep(0.2)
            assert val and base64.b64decode(val[0]["Value"]) == b"99"
            # kill -9: heartbeats stop -> kernel suspicion/Lifeguard ->
            # dead verdict -> EV_FAILED -> leader reconcile ->
            # serfHealth critical (the consul/serf.go:90-110 ->
            # leader.go:423 pipeline, with the kernel deciding timing)
            victim = servers[2]
            victim.proc.kill()
            deadline = time.monotonic() + 60
            crit = []
            while time.monotonic() < deadline:
                try:
                    crit = servers[0].http_get("/v1/health/state/critical")
                    if any(c["Node"] == "bb-t3"
                           and c["CheckID"] == "serfHealth" for c in crit):
                        break
                except Exception:
                    pass
                time.sleep(0.5)
            assert any(c["Node"] == "bb-t3" and c["CheckID"] == "serfHealth"
                       for c in crit), crit
        except Exception:
            print("--- plane ---")
            print(plane.output()[-3000:])
            for s in servers:
                print(f"--- {s.name} ---")
                print(s.output()[-3000:])
            raise
        finally:
            for s in servers:
                s.stop()
            plane.stop()


class TestObservabilityBlackBox:
    def test_trace_propagates_across_cluster(self):
        """PR-1 acceptance: one KV write through a 3-node cluster yields
        a single trace with the full hop chain (http root -> rpc
        forward -> raft apply -> fsm) retrievable from
        /v1/agent/traces, with the trace id carried over the wire
        between real processes and the leader's spans backhauled to the
        originating agent."""
        import os
        dbg = {"enable_debug": True}
        s1 = TestServer("bb-o1", bootstrap=False, bootstrap_expect=3,
                        config_extra=dbg).start()
        servers = [s1]
        try:
            s1.wait_for_api()
            for name in ("bb-o2", "bb-o3"):
                s = TestServer(name, bootstrap=False, bootstrap_expect=3,
                               retry_join=[s1.lan_addr],
                               config_extra=dbg).start()
                servers.append(s)
                s.wait_for_api()
            for s in servers:
                s.wait_for_leader(60)
            leader = servers[0].http_get("/v1/status/leader")
            follower = next(s for s in servers if s.name != leader)
            assert follower.http_put("/v1/kv/obs/trace-probe", b"x") is True
            # poll the FOLLOWER's ring: the write entered there, so the
            # whole stitched trace must come back from that agent
            deadline = time.monotonic() + 20
            trace = None
            while time.monotonic() < deadline:
                for t in follower.http_get("/v1/agent/traces?limit=50"):
                    names = {sp["Name"] for sp in t["Spans"]}
                    if "http:kvs" in names and "fsm:kvs" in names:
                        trace = t
                        break
                if trace:
                    break
                time.sleep(0.3)
            assert trace is not None, \
                follower.http_get("/v1/agent/traces?limit=50")
            spans = trace["Spans"]
            assert len(spans) >= 4
            # one trace id across every span, including the remote ones
            assert {sp["TraceID"] for sp in spans} == {trace["TraceID"]}
            names = {sp["Name"] for sp in spans}
            assert {"http:kvs", "rpc-forward:Server.Apply",
                    "rpc:Server.Apply", "raft-apply", "fsm:kvs"} <= names
            # spans recorded by ANOTHER process prove wire propagation
            nodes = {sp["Node"] for sp in spans}
            assert leader in nodes and follower.name in nodes
            # parentage: the remote server span hangs off the forward
            by_name = {sp["Name"]: sp for sp in spans}
            fwd = by_name["rpc-forward:Server.Apply"]
            assert by_name["rpc:Server.Apply"]["ParentID"] == fwd["SpanID"]
            assert by_name["http:kvs"]["ParentID"] is None
        except Exception:
            for s in servers:
                print(f"--- {s.name} ---")
                print(s.output()[-2000:])
            raise
        finally:
            for s in servers:
                s.stop()

    def test_raft_telemetry_and_debug_bundle(self):
        """Consensus-plane observatory acceptance: a lease-holding
        leader's Prometheus scrape carries the consul_raft_* histogram
        ladders and per-peer replication gauges (check_prom-clean), and
        a debug bundle pulled from a live 3-node cluster has the full
        manifest (metrics / slo / traces / flight / raft / tasks)."""
        import io
        import json as _json
        import tarfile
        import urllib.request

        from tools.check_prom import _iter_series, _require_ok, check_text

        def raw(s, path):
            with urllib.request.urlopen(s._url(path), timeout=30) as r:
                return r.read()

        dbg = {"enable_debug": True}
        s1 = TestServer("bb-d1", bootstrap=False, bootstrap_expect=3,
                        config_extra=dbg).start()
        servers = [s1]
        try:
            s1.wait_for_api()
            for name in ("bb-d2", "bb-d3"):
                s = TestServer(name, bootstrap=False, bootstrap_expect=3,
                               retry_join=[s1.lan_addr],
                               config_extra=dbg).start()
                servers.append(s)
                s.wait_for_api()
            for s in servers:
                s.wait_for_leader(60)
            leader_name = servers[0].http_get("/v1/status/leader")
            leader = next(s for s in servers if s.name == leader_name)
            followers = [s.name for s in servers if s is not leader]
            # raft traffic + a lease-path consistent read on the leader
            assert leader.http_put("/v1/kv/obs/bundle-probe", b"x") is True
            leader.http_get("/v1/kv/obs/bundle-probe?consistent")

            text = raw(leader,
                       "/v1/agent/metrics?format=prometheus").decode()
            errors = check_text(text)
            assert errors == [], errors
            series = list(_iter_series(text))
            for want in [
                    'consul_raft_append_quorum_ms_bucket{le="+Inf"}',
                    'consul_raft_commit_apply_ms_bucket{le="+Inf"}',
                    'consul_raft_lease_margin_ms_bucket{le="+Inf"}',
                    'consul_consistent_reads_total{path="lease"}',
                    'consul_antientropy_sync_ms_bucket{le="+Inf"}'] + [
                    f'consul_raft_peer_match_lag_entries{{peer="{p}"}}'
                    for p in followers]:
                assert _require_ok(want, series, errors), \
                    f"scrape missing {want}"
            # scrape hygiene gauges ride every agent's exposition
            fam_names = {n for n, _ in series}
            assert "consul_build_info" in fam_names
            assert "consul_up" in fam_names
            # stats rows ride /v1/agent/self on every node
            stats = leader.http_get("/v1/agent/self")["Stats"]["raft"]
            assert "leadership_gained" in stats

            # telemetry route (always-on) from a follower
            t = next(s for s in servers if s is not leader).http_get(
                "/v1/operator/raft/telemetry")
            assert "raft" in t and "timeline" in t and "antientropy" in t

            # one-shot bundle from the leader
            data = raw(leader, "/v1/agent/debug/bundle?seconds=1")
            with tarfile.open(fileobj=io.BytesIO(data), mode="r:gz") as tar:
                names = set(tar.getnames())
                manifest = _json.load(tar.extractfile("manifest.json"))
                assert {"metrics", "slo", "traces", "flight", "raft",
                        "device", "tasks"} <= set(manifest["sections"])
                assert manifest["node"] == leader.name
                for want in ("metrics/prometheus.txt", "raft/telemetry.json",
                             "device/telemetry.json", "tasks.txt",
                             "config.json"):
                    assert want in names, names
                dt = _json.load(tar.extractfile("device/telemetry.json"))
                assert "enabled" in dt and "build" in dt
                rt = _json.load(tar.extractfile("raft/telemetry.json"))
                assert rt["raft"]["state"] == "Leader"
                assert any(ev["kind"] == "leader-elected"
                           for ev in rt["timeline"])
                assert "asyncio tasks" in \
                    tar.extractfile("tasks.txt").read().decode()
        except Exception:
            for s in servers:
                print(f"--- {s.name} ---")
                print(s.output()[-2000:])
            raise
        finally:
            for s in servers:
                s.stop()

    def test_sigusr1_dumps_metrics(self):
        """SIGUSR1 -> telemetry dump on stderr (agent.go:623-631 role),
        against a real forked process."""
        import os
        import signal as _signal
        s = TestServer("bb-usr1").start()
        try:
            s.wait_for_api()
            s.wait_for_leader()
            s.http_put("/v1/kv/usr1/x", b"1")  # generate some telemetry
            os.kill(s.proc.pid, _signal.SIGUSR1)
            deadline = time.monotonic() + 15
            out = ""
            while time.monotonic() < deadline:
                out = s.output()
                if "[C]" in out and "[S]" in out:
                    break
                time.sleep(0.3)
            assert "[C]" in out, out[-2000:]   # counters (raft.apply)
            assert "[S]" in out, out[-2000:]   # samples (http timing)
            assert "raft.apply" in out, out[-2000:]
        except Exception:
            print(s.output()[-2000:])
            raise
        finally:
            s.stop()
