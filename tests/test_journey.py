"""Transition-journey observatory tests (obs/journey.py, ISSUE 19).

Covers the ledger at three levels:

* unit — the armed-batch protocol (arm / stage notes / close / parked
  wake / wakeless fallback / abort) and the family exposition against
  tools/check_prom's strict checker;
* hook — the membership backend's decode-stage stamping from the
  evbatch ``jt`` carriage, including the cross-process clock guard,
  and the compiled-out leg (``journey.journey is None`` must make
  every hook a no-op on a live cluster);
* end-to-end — a 3-node in-process cluster where the ledger's
  end-to-end latency must agree with an independent harness
  measurement of the same event (detection to first watcher served
  fresh data), the acceptance bar bench_fuse enforces at scale.
"""

from __future__ import annotations

import asyncio
import time

import pytest

from consul_tpu.membership.serf import SerfConfig
from consul_tpu.membership.swim import STATE_ALIVE, Node
from consul_tpu.membership.tpu_backend import TpuSerfPool
from consul_tpu.obs import journey as journey_mod
from consul_tpu.obs import raftstats
from consul_tpu.obs.journey import STAGES, JourneyStats
from consul_tpu.structs.structs import (
    HEALTH_PASSING, QueryOptions, SERF_CHECK_ID)

from tests.test_server_cluster import (
    make_servers, start_and_elect, stop_all, wait_until)

# Mirror of the governing obs/journey.py STAGES tuple — pinned by the
# vet table-drift pass (journey-stage union group).
JOURNEY_STAGES = ("detect", "drain", "decode", "enqueue", "submit",
                  "append_quorum", "fsm_apply", "render", "wake")


def _rec(name: str, t0: float) -> dict:
    return {"name": name, "t0": t0, "t_enq": t0, "stages": {}}


def test_stage_enum_mirrors_governing_tuple():
    assert JOURNEY_STAGES == STAGES


# -- armed-batch protocol --------------------------------------------------


class TestArmedBatch:
    def test_wake_midflight_finalizes_at_close(self):
        j = JourneyStats(budget=250.0)
        t0 = time.monotonic()
        j.arm([_rec("a", t0), _rec("b", t0)], time.monotonic())
        j.note_quorum(3.0)
        j.note_fsm_apply(1.0)
        j.note_render(0.2)
        j.note_wake()          # a watcher woke while the batch is armed
        j.close()
        assert j.transitions_total == 2
        assert j.wakeless_total == 0
        assert j.stage["wake"].wire()["count"] == 1
        recs = j.records()
        assert [r["name"] for r in recs] == ["a", "b"]
        for r in recs:
            assert r["e2e_ms"] >= 0.0
            for s in ("submit", "append_quorum", "fsm_apply", "render",
                      "wake"):
                assert s in r["stages"], f"record missing stage {s}"

    def test_parked_batch_finalized_by_wake(self):
        """close() before any watcher ran parks the batch; the first
        fresh-data long-poll return finalizes it with the wake stamp."""
        j = JourneyStats(budget=250.0)
        j.arm([_rec("a", time.monotonic())], time.monotonic())
        j.close()
        assert j.transitions_total == 0     # parked, nothing folded yet
        j.note_wake()
        assert j.transitions_total == 1
        assert j.wakeless_total == 0
        assert j.stage["wake"].wire()["count"] == 1
        assert j.records()[0]["name"] == "a"

    def test_parked_batch_wakeless_fallback_on_next_arm(self):
        j = JourneyStats(budget=250.0)
        j.arm([_rec("a", time.monotonic())], time.monotonic())
        j.close()
        j.arm([_rec("b", time.monotonic())], time.monotonic())
        assert j.transitions_total == 1     # "a" folded, bounded at close
        assert j.wakeless_total == 1
        assert j.stage["wake"].wire()["count"] == 0

    def test_abort_discards_armed_batch(self):
        j = JourneyStats(budget=250.0)
        j.arm([_rec("a", time.monotonic())], time.monotonic())
        j.abort()
        j.note_wake()                       # nothing armed or parked
        assert j.transitions_total == 0
        assert j.aborted_total == 1
        assert j.records() == []

    def test_negative_stage_deltas_dropped(self):
        j = JourneyStats(budget=250.0)
        j.stage_observe("decode", -1.0)
        assert j.stage["decode"].wire()["count"] == 0
        j.stage_observe("decode", 0.5)
        assert j.stage["decode"].wire()["count"] == 1

    def test_wire_shape(self):
        j = JourneyStats(budget=250.0)
        w = j.wire()
        assert w["enabled"] is True
        assert w["budget_ms"] == 250.0
        assert set(w["stages"]) == set(STAGES)
        for key in ("e2e", "slo", "transitions_total", "wakeless_total",
                    "aborted_total", "records"):
            assert key in w, f"wire missing {key!r}"
        assert journey_mod.disabled_wire()["enabled"] is False


# -- exposition ------------------------------------------------------------


def test_families_pass_check_prom():
    from consul_tpu.obs.prom import render_prometheus
    from tools.check_prom import check_text

    j = JourneyStats(budget=250.0)
    j.stage_observe("detect", 1.0)
    j.arm([_rec("x", time.monotonic())], time.monotonic())
    j.note_quorum(2.0)
    j.note_wake()
    j.close()
    hists, counters = j.families()
    text = render_prometheus([], histograms=hists,
                             labeled_counters=counters)
    assert check_text(text) == []
    # The stage ladder renders every label, zero-count stages included.
    for s in STAGES:
        assert f'consul_journey_stage_ms_bucket{{stage="{s}"' in text, \
            f"stage {s} ladder missing from exposition"
    assert "consul_journey_e2e_ms_bucket" in text
    assert 'consul_journey_transitions_total{outcome="visible"}' in text
    assert "consul_journey_wakeless_total" in text


# -- backend decode-stage hook ---------------------------------------------


class TestDecodeHook:
    def _pool(self, events):
        return TpuSerfPool(SerfConfig(node_name="jt-test"),
                           on_event=lambda k, n: events.append((k, n)))

    def test_evbatch_jt_carriage_stamps_and_reattaches(self):
        saved = journey_mod.journey
        journey_mod.journey = j = JourneyStats(budget=250.0)
        try:
            events = []
            pool = self._pool(events)
            t_detect = time.monotonic() - 0.010
            t_flush = time.monotonic() - 0.002
            pool._handle_member_event(
                "member-join", {"name": "n0", "addr": "10.0.0.1",
                                "port": 8301, "state": "alive"},
                [t_detect, t_flush, 1.25])
            assert len(events) == 1
            node = events[0][1]
            rec = node._journey
            assert rec["t0"] == t_detect
            assert rec["stages"]["detect"] == 1.25
            assert rec["stages"]["drain"] >= 0.0
            assert rec["stages"]["decode"] >= 0.0
            assert j.stage["decode"].wire()["count"] == 1
        finally:
            journey_mod.journey = saved

    def test_cross_process_clock_guard_reanchors_t0(self):
        """A jt stamped by another process's monotonic clock can sit in
        our future; the decode hook must re-anchor t0 at decode time
        instead of producing a negative journey."""
        saved = journey_mod.journey
        journey_mod.journey = JourneyStats(budget=250.0)
        try:
            events = []
            pool = self._pool(events)
            future = time.monotonic() + 3600.0
            pool._handle_member_event(
                "member-join", {"name": "n1", "addr": "10.0.0.2",
                                "port": 8301, "state": "alive"},
                [future, future, 1.0])
            rec = events[0][1]._journey
            assert rec["t0"] <= time.monotonic()
        finally:
            journey_mod.journey = saved

    def test_sink_installed_on_reset(self):
        saved = journey_mod.journey
        try:
            j = JourneyStats(budget=250.0)
            journey_mod._install(j)
            assert raftstats.journey_sink is j
            j.note_quorum(5.0)      # what note_commit forwards
            assert j.stage["append_quorum"].wire()["count"] == 1
        finally:
            journey_mod.journey = saved
            journey_mod._install(saved)


# -- compiled-out leg ------------------------------------------------------


def test_compiled_out_hooks_are_noops_on_live_cluster():
    """With the ledger compiled out (CONSUL_TPU_JOURNEY=0 makes the
    module singleton None), every hook along the fused path must reduce
    to its attribute test: a transition still lands in the catalog."""
    saved_j, saved_sink = journey_mod.journey, raftstats.journey_sink
    journey_mod.journey = None
    raftstats.journey_sink = None

    async def main():
        _, servers = make_servers(3)
        leader = await start_and_elect(servers)
        leader.membership_notify("member-join", Node(
            name="dark0", addr="10.5.0.1", port=8301, state=STATE_ALIVE))

        def landed():
            _, checks = leader.store.node_checks("dark0")
            return any(c.check_id == SERF_CHECK_ID
                       and c.status == HEALTH_PASSING for c in checks)

        await wait_until(landed, msg="transition applied with ledger off")
        await stop_all(servers)

    try:
        asyncio.run(main())
    finally:
        journey_mod.journey = saved_j
        raftstats.journey_sink = saved_sink


# -- end-to-end agreement --------------------------------------------------


@pytest.mark.skipif(journey_mod.journey is None,
                    reason="journey ledger compiled out")
def test_e2e_agrees_with_harness_measurement():
    """One member burst against a 3-node cluster with a held watcher
    per member: the ledger's worst per-record e2e must agree with the
    harness's first-visible stamp (same two endpoints: the notify call
    and the first long-poll served fresh data) — the in-process twin of
    the bench_fuse 20% acceptance gate, with an absolute floor so a
    sub-millisecond jitter can't flake the relative bar."""
    async def main():
        jy = journey_mod.journey
        _, servers = make_servers(3)
        leader = await start_and_elect(servers)
        await asyncio.sleep(0.3)   # boot reconciles settle
        jy.reset()
        names = [f"jm{i}" for i in range(8)]
        t0s: dict = {}
        harness: list = []

        async def watch(nm: str) -> None:
            idx = 1
            while True:
                meta, checks = await leader.health.node_checks(
                    nm, QueryOptions(min_query_index=idx,
                                     max_query_time=2.0))
                serf = next((c for c in checks
                             if c.check_id == SERF_CHECK_ID), None)
                if serf is not None and serf.status == HEALTH_PASSING:
                    harness.append((time.monotonic() - t0s[nm]) * 1000.0)
                    return
                idx = max(idx, meta.index, 1)

        watchers = [asyncio.create_task(watch(nm)) for nm in names]
        await asyncio.sleep(0.1)   # watchers parked on min_index
        for nm in names:
            t0s[nm] = time.monotonic()
            leader.membership_notify("member-join", Node(
                name=nm, addr="10.5.1.1", port=8301, state=STATE_ALIVE))
        await asyncio.wait_for(asyncio.gather(*watchers), timeout=15.0)

        recs = [r for r in jy.records() if r["name"] in set(names)]
        assert len(recs) == len(names), \
            f"ledger closed {len(recs)}/{len(names)} burst records"
        ledger_ms = max(r["e2e_ms"] for r in recs)
        first_visible_ms = min(harness)
        tol = max(0.25 * first_visible_ms, 5.0)
        assert abs(ledger_ms - first_visible_ms) <= tol, \
            (f"journey e2e {ledger_ms:.2f}ms vs harness first-visible "
             f"{first_visible_ms:.2f}ms exceeds ±{tol:.2f}ms")
        # The pipeline stages behind that number must all have fired.
        sums = jy.stage_sums()
        for s in ("submit", "append_quorum", "fsm_apply"):
            assert sums[s] > 0.0, f"stage {s} never folded"
        assert jy.stage["wake"].wire()["count"] >= 1
        await stop_all(servers)

    asyncio.run(main())


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
