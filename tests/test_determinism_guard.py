"""Replicated-path determinism lint.

Parity target: the reference's ``scripts/verify_no_uuid.sh`` (run by
``make test``, Makefile:37): UUIDs — and any other nondeterminism — must
be generated *outside* the FSM/state-store apply path, or follower state
machines diverge.  Session/ACL IDs are minted in the endpoints on the
leader (consul/session_endpoint.go:60-74) before the entry hits the log.
"""

import io
import re
import tokenize
from pathlib import Path

REPLICATED_MODULES = [
    "consul_tpu/consensus/fsm.py",
    "consul_tpu/state/store.py",
    "consul_tpu/state/radix.py",
    "consul_tpu/state/notify.py",
]

# time.monotonic is allowed in store.py ONLY for the lock-delay map, which
# the reference also keeps node-local and out of replicated state
# (state_store.go:1461-1467 — "must be checked on the leader ... due to
# the variability of clocks").
FORBIDDEN = [
    (re.compile(r"\buuid\b", re.I), "uuid generation"),
    (re.compile(r"time\.time\(\)"), "wall-clock read"),
    (re.compile(r"\brandom\.|np\.random|secrets\."), "randomness"),
    (re.compile(r"os\.urandom"), "randomness"),
]


def _code_tokens(text):
    """Source tokens excluding comments and string literals/docstrings."""
    for tok in tokenize.generate_tokens(io.StringIO(text).readline):
        if tok.type not in (tokenize.COMMENT, tokenize.STRING):
            yield tok


def test_no_nondeterminism_in_replicated_path():
    root = Path(__file__).resolve().parent.parent
    violations = []
    for rel in REPLICATED_MODULES:
        text = (root / rel).read_text()
        for tok in _code_tokens(text):
            for pat, why in FORBIDDEN:
                if pat.search(tok.string):
                    violations.append(
                        f"{rel}:{tok.start[0]}: {why}: {tok.line.strip()}")
    assert not violations, "\n".join(violations)


def test_lock_delay_is_only_monotonic_use():
    root = Path(__file__).resolve().parent.parent
    text = (root / "consul_tpu/state/store.py").read_text()
    uses = [l for l in text.splitlines() if "time.monotonic" in l.split("#")[0]]
    # Every monotonic read must be in lock-delay bookkeeping.
    ok_markers = ("_lock_delay", "expires", "rem = ")
    for line in uses:
        assert any(m in line or m in text[max(0, text.find(line) - 400):text.find(line)]
                   for m in ok_markers), f"unexpected clock read: {line.strip()}"
