"""Device-resident state store (state/device_store.py): deterministic
replay, host/device lockstep, FSM batching integration, rebuild after
restore, the hotpath byte cache, and the storestats exposition.

The crossval oracle is the contract (ISSUE: bit-identical verdicts,
fired sets, and wakeups on the forced 8-CPU-device mesh — conftest.py
sets the mesh).  The suite keeps the fast sizing; the full vet-gate
sweep lives in tools/store_crossval.py and the heavy tier here is
``@pytest.mark.slow``.
"""

import types

import numpy as np
import pytest

from consul_tpu.state.device_store import (
    DeviceStoreBridge, crossval)
from consul_tpu.state.store import StateStore
from consul_tpu.structs import codec
from consul_tpu.structs.structs import (
    DirEntry, KVSOp, KVSRequest, MessageType)


def _kv_entry(key, value=b"v", op=KVSOp.SET.value, modify_index=0,
              flags=0):
    d = DirEntry(key=key, value=value)
    d.flags = flags
    if modify_index:
        d.modify_index = modify_index
    req = KVSRequest(op=op, dir_ent=d)
    return bytes([MessageType.KVS]) + codec.encode_payload(req)


def _batches(seed=0, n_batches=6, batch=8):
    """A deterministic (index, data, ctx) entry stream with set /
    delete / delete-tree / cas mixed in."""
    rng = np.random.default_rng(seed)
    out, index = [], 10
    for _ in range(n_batches):
        entries = []
        for _ in range(batch):
            index += 1
            r = rng.random()
            key = f"app/{int(rng.integers(12))}/k{int(rng.integers(6))}"
            if r < 0.55:
                data = _kv_entry(key, b"v%d" % index)
            elif r < 0.75:
                data = _kv_entry(key, op=KVSOp.DELETE.value)
            elif r < 0.9:
                data = _kv_entry(f"app/{int(rng.integers(12))}/",
                                 op=KVSOp.DELETE_TREE.value)
            else:
                data = _kv_entry(key, b"c%d" % index, op=KVSOp.CAS.value)
            entries.append((index, data, None))
        out.append(entries)
    return out


def _fsm_with_bridge(capacity=1 << 9):
    from consul_tpu.consensus.fsm import ConsulFSM

    fsm = ConsulFSM()
    # match_backend forced: these tests exist to exercise the device
    # matcher + lockstep cross-check, which the CPU auto-gate would
    # otherwise skip (test_watch_match_auto_gate pins the gate itself).
    fsm.attach_device_store(DeviceStoreBridge(capacity=capacity, probe=16,
                                              stats=None,
                                              match_backend="device"))
    return fsm


class TestDeterministicReplay:
    def test_same_stream_identical_table(self):
        """Tier-1 pin for the acceptance criterion: replaying the same
        batch sequence yields a bit-identical device table."""
        tabs = []
        for _ in range(2):
            fsm = _fsm_with_bridge()
            for entries in _batches(seed=3):
                fsm.apply_batch(entries)
            assert fsm.device.divergence == 0
            tabs.append(fsm.device.table.tab)
        for a, b in zip(*tabs):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_replay_after_reset_matches(self):
        fsm = _fsm_with_bridge()
        stream = _batches(seed=7, n_batches=4)
        for entries in stream:
            fsm.apply_batch(entries)
        first = [np.asarray(a).copy() for a in fsm.device.table.tab]
        fsm.device.table.reset()
        fsm2 = _fsm_with_bridge()
        for entries in stream:
            fsm2.apply_batch(entries)
        for a, b in zip(first, fsm2.device.table.tab):
            assert np.array_equal(a, np.asarray(b))


class TestCrossval:
    def test_fast_oracle(self):
        """In-suite slice of the crossval contract (the full sweep is
        tools/store_crossval.py in `make vet`)."""
        summary = crossval(n_batches=5, batch=12, n_watches=48,
                           capacity=1 << 10, seed=1)
        assert summary["divergence"] == 0
        assert summary["degraded"] == 0

    @pytest.mark.slow
    def test_full_oracle_sweep(self):
        for seed in range(3):
            summary = crossval(n_batches=20, batch=32, n_watches=200,
                               capacity=1 << 12, seed=seed)
            assert summary["divergence"] == 0


class TestFSMIntegration:
    def test_batch_verdicts_lockstep(self):
        fsm = _fsm_with_bridge()
        for entries in _batches(seed=5):
            fsm.apply_batch(entries)
        assert fsm.device.divergence == 0
        live, _tomb, degraded = fsm.device.occupancy()
        assert degraded == 0
        assert live == len(fsm.store.kvs_list("")[2])

    def test_results_match_sequential(self):
        """Same entries through a bridged and a plain FSM return the
        same per-entry results (CAS verdicts included)."""
        from consul_tpu.consensus.fsm import ConsulFSM

        plain, bridged = ConsulFSM(), _fsm_with_bridge()
        for entries in _batches(seed=9, n_batches=4):
            r_plain = plain.apply_batch(entries)
            r_bridged = bridged.apply_batch(entries)
            assert r_plain == r_bridged
        a = {e.key: (e.modify_index, e.value)
             for _, e in (plain.store.kvs_get(k)
                          for k in _all_keys(plain.store))}
        b = {e.key: (e.modify_index, e.value)
             for _, e in (bridged.store.kvs_get(k)
                          for k in _all_keys(bridged.store))}
        assert a == b

    def test_bridge_failure_degrades_to_host(self):
        fsm = _fsm_with_bridge()

        def boom(cap, store):
            raise RuntimeError("device fell over")

        fsm.device.on_batch = boom
        entries = [(21, _kv_entry("deg/a", b"x"), None),
                   (22, _kv_entry("deg/b", b"y"), None)]
        results = fsm.apply_batch(entries)
        assert results == [None, None]
        _, ent = fsm.store.kvs_get("deg/a")
        assert ent is not None and ent.modify_index == 21

    def test_watch_fires_through_batch(self):
        fsm = _fsm_with_bridge()
        fired = []

        class Flag:
            def set(self):
                fired.append(True)

        fsm.store.watch_kv("app/", Flag())
        fsm.apply_batch([(31, _kv_entry("app/1/k0", b"z"), None)])
        assert fired and fsm.device.divergence == 0


def _all_keys(store):
    return [e.key for e in store.kvs_list("")[2]]


class TestRestoreRebuild:
    def test_restore_reseeds_device(self):
        fsm = _fsm_with_bridge()
        for entries in _batches(seed=11, n_batches=3):
            fsm.apply_batch(entries)
        live_before = fsm.device.occupancy()[0]
        snap = fsm.snapshot(999)

        fsm2 = _fsm_with_bridge()
        fsm2.restore(snap)
        assert fsm2.device.occupancy()[0] == live_before
        # Post-restore applies stay lockstep (create/modify split held).
        for entries in _batches(seed=12, n_batches=2):
            fsm2.apply_batch(entries)
        assert fsm2.device.divergence == 0


class TestByteCache:
    def _srv(self):
        store = StateStore()
        return types.SimpleNamespace(store=store)

    def test_hit_and_write_invalidation(self):
        from consul_tpu.agent.hotpath import KVByteCache

        srv = self._srv()
        srv.store.kvs_set(5, DirEntry(key="c/a", value=b"one"))
        cache = KVByteCache(srv)
        row = cache.render("c/a")
        assert row[1] == 200 and b"c/a" in row[3]
        assert cache.lookup("c/a") == row and cache.hits == 1
        srv.store.kvs_set(6, DirEntry(key="c/other", value=b"two"))
        assert cache.lookup("c/a") is None  # any write invalidates
        row2 = cache.render("c/a")
        assert row2[0] == 6 and row2[4] == 5  # header index = entry's

    def test_miss_renders_404(self):
        from consul_tpu.agent.hotpath import KVByteCache

        cache = KVByteCache(self._srv())
        row = cache.render("nope")
        assert row[1] == 404 and row[3] == b""

    def test_refresh_only_cached_keys(self):
        from consul_tpu.agent.hotpath import KVByteCache

        srv = self._srv()
        srv.store.kvs_set(5, DirEntry(key="c/a", value=b"one"))
        cache = KVByteCache(srv)
        cache.render("c/a")
        srv.store.kvs_set(6, DirEntry(key="c/a", value=b"two"))
        srv.store.kvs_set(7, DirEntry(key="c/b", value=b"three"))
        cache.refresh(["c/a", "c/b"])
        assert cache.lookup("c/a")[3].find(b"dHdv") >= 0  # b64("two")
        assert "c/b" not in cache.entries  # never asked for -> not warmed

    def test_fifo_bound(self):
        from consul_tpu.agent.hotpath import KVByteCache

        srv = self._srv()
        cache = KVByteCache(srv, max_entries=4)
        for i in range(8):
            cache.render(f"k{i}")
        assert len(cache.entries) == 4
        assert "k0" not in cache.entries and "k7" in cache.entries

    def test_attach_sets_render_hook(self):
        from consul_tpu.agent.hotpath import attach_kv_cache

        srv = self._srv()
        bridge = types.SimpleNamespace(render_hook=None)
        cache = attach_kv_cache(srv, bridge)
        assert srv.kv_byte_cache is cache
        assert bridge.render_hook == cache.refresh


class TestWatchMatchAutoGate:
    """The match_backend auto-gate (DeviceStoreBridge): on this CPU box
    the device matcher loses by ~23x (BENCH_WATCH.json), so production
    batches must take the host radix walk — and say so on the gauge."""

    def _bridged_fsm(self, stats):
        from consul_tpu.consensus.fsm import ConsulFSM

        fsm = ConsulFSM()
        fsm.attach_device_store(
            DeviceStoreBridge(capacity=1 << 9, stats=stats))
        return fsm

    def test_auto_chooses_host_on_cpu(self):
        from consul_tpu.obs.storestats import StoreStats

        stats = StoreStats()
        fsm = self._bridged_fsm(stats)
        fired = []

        class Flag:
            def set(self):
                fired.append(True)

        fsm.store.watch_kv("gate/", Flag())
        fsm.apply_batch([(41, _kv_entry("gate/k", b"v"), None)])
        # Decision recorded, host leg selected, device matcher skipped
        # entirely — but the (host-authoritative) watch still fired.
        assert stats.match_backend_device is False
        assert stats.match_events == 0
        assert fired
        assert fsm.device.divergence == 0

    def test_gate_heuristic_and_overrides(self):
        from consul_tpu.state.device_store import WATCH_DEVICE_MIN_CPU

        b = DeviceStoreBridge(capacity=64, stats=None)
        assert b._platform == "cpu" and b.match_backend == "auto"
        assert b._use_device_match() is False
        # Non-CPU backend: device unconditionally.
        b._platform = "tpu"
        assert b._use_device_match() is True
        # CPU past the standing-watch floor: device.
        b._platform = "cpu"
        b._w_groups = [("p", None)] * WATCH_DEVICE_MIN_CPU
        assert b._use_device_match() is True
        # Explicit overrides beat the heuristic both ways.
        b.match_backend = "host"
        assert b._use_device_match() is False
        b.match_backend = "device"
        b._w_groups = []
        assert b._use_device_match() is True
        with pytest.raises(ValueError):
            # deliberately invalid backend name — the point of the test
            DeviceStoreBridge(capacity=64, stats=None,
                              match_backend="maybe")  # noqa: K02

    def test_forced_device_still_crosschecks(self):
        from consul_tpu.obs.storestats import StoreStats

        from consul_tpu.consensus.fsm import ConsulFSM

        stats = StoreStats()
        fsm = ConsulFSM()
        fsm.attach_device_store(DeviceStoreBridge(
            capacity=1 << 9, stats=stats, match_backend="device"))

        class Flag:
            def set(self):
                pass

        fsm.store.watch_kv("gate/", Flag())
        fsm.apply_batch([(51, _kv_entry("gate/k", b"v"), None)])
        assert stats.match_backend_device is True
        assert stats.match_events > 0
        assert fsm.device.divergence == 0

    def test_backend_gauge_exported(self):
        from consul_tpu.obs.prom import render_prometheus
        from consul_tpu.obs.storestats import StoreStats
        from tools.check_prom import check_text

        stats = StoreStats()
        _h, gauges, _c = stats.families()
        assert not any(g["name"] == "consul_watch_match_backend"
                       for g in gauges)  # no decision yet -> no row
        stats.match_backend_device = False
        hists, gauges, counters = stats.families()
        rows = [g for g in gauges
                if g["name"] == "consul_watch_match_backend"]
        assert rows and rows[0]["rows"][0][1] == 0.0
        text = render_prometheus([], histograms=hists,
                                 labeled_counters=counters,
                                 labeled_gauges=gauges)
        assert check_text(text) == []
        assert "consul_watch_match_backend" in text


class TestStoreStatsExposition:
    def test_families_pass_strict_checker(self):
        from consul_tpu.obs.prom import render_prometheus
        from consul_tpu.obs.storestats import StoreStats
        from tools.check_prom import check_text

        stats = StoreStats()
        stats.watch_registered = 7
        stats.note_apply(1.2, 16)
        stats.note_apply(0.4, 3)
        stats.note_match(0.8, 16, 5)
        hists, gauges, counters = stats.families(
            occupancy=(12, 3, 0), capacity=1 << 10)
        text = render_prometheus([], histograms=hists,
                                 labeled_counters=counters,
                                 labeled_gauges=gauges)
        assert check_text(text) == []
        for fam in ("consul_store_dispatch_ms_bucket",
                    "consul_store_apply_batch_entries_bucket",
                    "consul_store_applied_entries_total",
                    "consul_watch_fired_total",
                    "consul_store_divergence_total",
                    "consul_store_capacity",
                    "consul_store_occupancy",
                    "consul_watch_registered"):
            assert fam in text, fam

    def test_table_full_counter_only_when_degraded(self):
        from consul_tpu.obs.storestats import StoreStats

        stats = StoreStats()
        _h, _g, counters = stats.families(occupancy=(1, 0, 0), capacity=64)
        names = {c["name"] for c in counters}
        assert "consul_store_table_full_total" not in names
        _h, _g, counters = stats.families(occupancy=(1, 0, 2), capacity=64)
        names = {c["name"] for c in counters}
        assert "consul_store_table_full_total" in names


class TestServerWiring:
    def test_server_flag_attaches_bridge(self):
        from consul_tpu.server.server import Server, ServerConfig

        srv = Server(ServerConfig(device_store=True,
                                  device_store_capacity=1 << 9))
        assert srv.fsm.device is not None
        assert srv.fsm.device.capacity == 1 << 9

    def test_config_validation(self):
        from consul_tpu.agent.config import Config, validate_config

        cfg = Config(device_store=True, server=False)
        assert any("server mode" in p for p in validate_config(cfg))
        cfg = Config(device_store=True, server=True,
                     device_store_capacity=100)
        assert any("power of two" in p for p in validate_config(cfg))
        cfg = Config(device_store=True, server=True,
                     device_store_capacity=1 << 10, node_name="n1",
                     data_dir="/tmp/x")
        assert not any("device_store" in p for p in validate_config(cfg))
