"""C++ MVCC store tests (the LMDB/BoltDB-role native component,
SURVEY §2.1).  Skipped wholesale if the toolchain can't build it."""

import os
import threading

import pytest

from consul_tpu.native import NativeLogStore, NativeStore, native_available

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native toolchain unavailable")


@pytest.fixture()
def store(tmp_path):
    s = NativeStore(str(tmp_path / "t.cstore"))
    yield s
    s.close()


class TestKV:
    def test_put_get_delete(self, store):
        store.put(b"k1", b"v1")
        assert store.get(b"k1") == b"v1"
        store.put(b"k1", b"v2")
        assert store.get(b"k1") == b"v2"
        store.delete(b"k1")
        assert store.get(b"k1") is None
        assert store.get(b"never") is None

    def test_empty_value_and_binary_keys(self, store):
        store.put(b"empty", b"")
        assert store.get(b"empty") == b""
        key = bytes(range(256))[:200]
        store.put(key, b"\x00\xff binary")
        assert store.get(key) == b"\x00\xff binary"

    def test_prefix_scan_ordered(self, store):
        for k in (b"b/2", b"a", b"b/1", b"b/3", b"c"):
            store.put(k, k.upper())
        assert [k for k, _ in store.scan(b"b/")] == [b"b/1", b"b/2", b"b/3"]
        assert [k for k, _ in store.scan()] == [b"a", b"b/1", b"b/2", b"b/3", b"c"]
        assert [v for _, v in store.scan(b"b/")] == [b"B/1", b"B/2", b"B/3"]

    def test_mvcc_snapshot_isolation(self, store):
        store.put(b"x", b"old")
        snap = store.snapshot()
        store.put(b"x", b"new")
        store.put(b"y", b"born-later")
        store.delete(b"x")
        assert store.get(b"x", snap) == b"old"
        assert store.get(b"y", snap) is None
        assert store.get(b"x") is None
        assert [k for k, _ in store.scan(b"", snap)] == [b"x"]
        store.release(snap)

    def test_count_and_seq(self, store):
        assert store.count() == 0
        s1 = store.put(b"a", b"1")
        s2 = store.put(b"b", b"2")
        assert s2 > s1
        store.delete(b"a")
        assert store.count() == 1
        assert store.last_seq() > s2

    def test_compact_drops_history(self, store, tmp_path):
        for i in range(100):
            store.put(b"hot", str(i).encode())
        store.put(b"cold", b"keep")
        store.delete(b"hot")
        pre = os.path.getsize(tmp_path / "t.cstore")
        store.compact()
        post = os.path.getsize(tmp_path / "t.cstore")
        assert post < pre
        assert store.get(b"cold") == b"keep"
        assert store.get(b"hot") is None

    def test_compact_refused_with_pinned_snapshot(self, store):
        store.put(b"a", b"1")
        snap = store.snapshot()
        with pytest.raises(RuntimeError):
            store.compact()
        store.release(snap)
        store.compact()
        assert store.get(b"a") == b"1"

    def test_durability_replay(self, tmp_path):
        p = str(tmp_path / "d.cstore")
        s = NativeStore(p)
        for i in range(50):
            s.put(f"k{i:03d}".encode(), f"v{i}".encode())
        s.delete(b"k010")
        s.sync()
        s.close()
        s2 = NativeStore(p)
        assert s2.count() == 49
        assert s2.get(b"k011") == b"v11"
        assert s2.get(b"k010") is None
        s2.close()

    def test_torn_tail_recovery(self, tmp_path):
        p = str(tmp_path / "torn.cstore")
        s = NativeStore(p)
        s.put(b"good", b"record")
        s.sync()
        s.close()
        # corrupt: append garbage (simulates a torn write at crash)
        with open(p, "ab") as f:
            f.write(b"\x50\x00\x00\x00garbage-partial-record")
        s2 = NativeStore(p)
        assert s2.get(b"good") == b"record"
        # store still writable after truncating the torn tail
        s2.put(b"after", b"crash")
        assert s2.get(b"after") == b"crash"
        s2.close()

    def test_concurrent_readers(self, store):
        for i in range(500):
            store.put(f"key{i:04d}".encode(), str(i).encode())
        errors = []

        def reader():
            try:
                for _ in range(20):
                    snap = store.snapshot()
                    got = list(store.scan(b"key", snap))
                    assert len(got) == 500
                    store.release(snap)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=reader) for _ in range(4)]
        for t in threads:
            t.start()
        for _ in range(200):
            store.put(b"churn", os.urandom(32))
        for t in threads:
            t.join()
        assert errors == []


class TestNativeLogStore:
    def test_log_contract(self, tmp_path):
        from consul_tpu.consensus.log import LOG_COMMAND, LogEntry
        ls = NativeLogStore(str(tmp_path / "raft"))
        assert ls.first_index() == 0 and ls.last_index() == 0
        ls.append([LogEntry(index=i, term=1, type=LOG_COMMAND,
                            data=f"cmd{i}".encode()) for i in range(1, 11)])
        assert ls.first_index() == 1 and ls.last_index() == 10
        assert ls.get(5).data == b"cmd5"
        # conflict truncation
        ls.delete_from(8)
        assert ls.last_index() == 7 and ls.get(9) is None
        # snapshot compaction
        ls.delete_to(3)
        assert ls.first_index() == 4
        assert ls.get(2) is None and ls.get(4).data == b"cmd4"
        # stable store
        ls.set_stable("term", 7)
        ls.set_stable("voted_for", "n2")
        assert ls.get_stable("term") == 7
        ls.close()
        # reopen: everything durable
        ls2 = NativeLogStore(str(tmp_path / "raft"))
        assert ls2.first_index() == 4 and ls2.last_index() == 7
        assert ls2.get(6).data == b"cmd6"
        assert ls2.get_stable("voted_for") == "n2"
        assert ls2.get_stable("missing", "dflt") == "dflt"
        ls2.close()

    def test_server_uses_native_log(self, tmp_path):
        """Server with a data_dir picks the native store when buildable."""
        from consul_tpu.server.server import Server, ServerConfig
        srv = Server(ServerConfig(node_name="s1", data_dir=str(tmp_path)))
        assert type(srv.raft.log).__name__ == "NativeLogStore"
