"""Real-network membership tests: multi-node pools on loopback with
compressed timers (the reference tier: memberlist/serf behavior driven
through consul/server_test.go-style in-process clusters, SURVEY §4)."""

import asyncio
import base64
import os

import pytest

from consul_tpu.membership import SerfConfig, SerfPool
from consul_tpu.membership.serf import (
    EV_USER, client_tags, parse_server, server_tags)
from consul_tpu.membership.swim import (
    EV_FAILED, EV_LEAVE, STATE_ALIVE, STATE_DEAD, STATE_LEFT)


@pytest.fixture()
def loop():
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    yield loop
    loop.close()


def _fast(name, tags=None, snapshot_path="", **kw):
    return SerfConfig(node_name=name, bind_addr="127.0.0.1",
                      tags=tags or {}, snapshot_path=snapshot_path,
                      probe_interval=0.05, probe_timeout=0.02,
                      gossip_interval=0.02, suspicion_mult=3.0,
                      push_pull_interval=1.0, **kw)


async def _wait(cond, timeout=10.0, interval=0.02):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if cond():
            return True
        await asyncio.sleep(interval)
    return False


async def _mk_pool(name, seeds=(), tags=None, keyring=None, events=None,
                   snapshot_path=""):
    handler = (lambda kind, payload: events.append((kind, payload))) \
        if events is not None else None
    pool = SerfPool(_fast(name, tags, snapshot_path), keyring=keyring,
                    on_event=handler)
    await pool.start()
    if seeds:
        assert await pool.join(list(seeds)) > 0
    return pool


class TestMembership:
    def test_three_node_join_and_members(self, loop):
        async def body():
            a = await _mk_pool("a", tags=server_tags("dc1", 8300))
            seed = [f"127.0.0.1:{a.local_addr[1]}"]
            b = await _mk_pool("b", seeds=seed, tags=server_tags("dc1", 8300))
            c = await _mk_pool("c", seeds=seed, tags=client_tags("dc1"))
            for p in (a, b, c):
                assert await _wait(lambda p=p: len(p.alive_members()) == 3), \
                    f"{p.config.node_name} sees {len(p.alive_members())}"
            # tag scheme parses into serverParts (consul/util.go)
            servers = [parse_server(n) for n in a.members()]
            assert sum(1 for s in servers if s) == 2
            assert all(s["dc"] == "dc1" and s["port"] == 8300
                       for s in servers if s)
            for p in (a, b, c):
                await p.stop()
        loop.run_until_complete(body())

    def test_failure_detection_and_events(self, loop):
        async def body():
            events = []
            a = await _mk_pool("a", events=events)
            seed = [f"127.0.0.1:{a.local_addr[1]}"]
            b = await _mk_pool("b", seeds=seed)
            c = await _mk_pool("c", seeds=seed)
            assert await _wait(lambda: len(a.alive_members()) == 3)
            await c.stop()  # hard kill: no leave broadcast
            assert await _wait(
                lambda: any(n.name == "c" and n.state == STATE_DEAD
                            for n in a.members()), timeout=15)
            assert any(k == EV_FAILED and n.name == "c"
                       for k, n in events if hasattr(n, "name"))
            # b converges on the same verdict by dissemination
            assert await _wait(
                lambda: any(n.name == "c" and n.state == STATE_DEAD
                            for n in b.members()), timeout=15)
            await a.stop()
            await b.stop()
        loop.run_until_complete(body())

    def test_graceful_leave(self, loop):
        async def body():
            events = []
            a = await _mk_pool("a", events=events)
            seed = [f"127.0.0.1:{a.local_addr[1]}"]
            b = await _mk_pool("b", seeds=seed)
            assert await _wait(lambda: len(a.alive_members()) == 2)
            await b.leave()
            await b.stop()
            assert await _wait(
                lambda: any(n.name == "b" and n.state == STATE_LEFT
                            for n in a.members()), timeout=15)
            assert any(k == EV_LEAVE and getattr(n, "name", "") == "b"
                       for k, n in events)
            await a.stop()
        loop.run_until_complete(body())

    def test_rejoin_after_failure(self, loop):
        async def body():
            a = await _mk_pool("a")
            seed = [f"127.0.0.1:{a.local_addr[1]}"]
            b = await _mk_pool("b")
            await b.join(seed)
            assert await _wait(lambda: len(a.alive_members()) == 2)
            b_port = b.local_addr[1]
            await b.stop()
            assert await _wait(
                lambda: any(n.name == "b" and n.state == STATE_DEAD
                            for n in a.members()), timeout=15)
            # restart under the same name; alive at higher incarnation wins
            b2 = SerfPool(SerfConfig(
                node_name="b", bind_addr="127.0.0.1", bind_port=b_port,
                probe_interval=0.05, probe_timeout=0.02,
                gossip_interval=0.02, suspicion_mult=3.0,
                push_pull_interval=1.0))
            await b2.start()
            b2.ml.incarnation = 10  # outlive the dead verdict
            b2.ml.nodes["b"].incarnation = 10
            await b2.join(seed)
            assert await _wait(
                lambda: any(n.name == "b" and n.state == STATE_ALIVE
                            for n in a.members()), timeout=15)
            await a.stop()
            await b2.stop()
        loop.run_until_complete(body())

    def test_user_event_floods(self, loop):
        async def body():
            got = {"a": [], "b": [], "c": []}
            pools = {}
            pools["a"] = await _mk_pool("a", events=got["a"])
            seed = [f"127.0.0.1:{pools['a'].local_addr[1]}"]
            pools["b"] = await _mk_pool("b", seeds=seed, events=got["b"])
            pools["c"] = await _mk_pool("c", seeds=seed, events=got["c"])
            assert await _wait(
                lambda: all(len(p.alive_members()) == 3
                            for p in pools.values()))
            pools["b"].user_event("deploy", b"v2")
            def all_got():
                return all(any(k == EV_USER and m["name"] == "deploy"
                               and m["payload"] == b"v2"
                               for k, m in evs if isinstance(m, dict))
                           for evs in got.values())
            assert await _wait(all_got, timeout=15)
            for p in pools.values():
                await p.stop()
        loop.run_until_complete(body())


class TestEncryption:
    def _keyring(self, tmp_path, key=None):
        from consul_tpu.agent.keyring import Keyring
        key = key or base64.b64encode(os.urandom(16)).decode()
        return Keyring(path=str(tmp_path / "kr.json"), initial_key=key), key

    def test_encrypted_pool_communicates(self, loop, tmp_path):
        async def body():
            kr1, key = self._keyring(tmp_path / "1")
            kr2, _ = self._keyring(tmp_path / "2", key)
            a = await _mk_pool("a", keyring=kr1)
            b = await _mk_pool("b", keyring=kr2)
            assert await b.join([f"127.0.0.1:{a.local_addr[1]}"])
            assert await _wait(lambda: len(a.alive_members()) == 2)
            await a.stop()
            await b.stop()
        loop.run_until_complete(body())

    def test_plaintext_rejected_by_encrypted_pool(self, loop, tmp_path):
        async def body():
            kr, _ = self._keyring(tmp_path)
            a = await _mk_pool("a", keyring=kr)
            b = SerfPool(_fast("b"))
            await b.start()
            n = await b.join([f"127.0.0.1:{a.local_addr[1]}"])
            assert n == 0  # push/pull reply undecryptable without the key
            assert len(a.alive_members()) == 1
            await a.stop()
            await b.stop()
        loop.run_until_complete(body())

    def test_wrong_key_rejected(self, loop, tmp_path):
        async def body():
            kr1, _ = self._keyring(tmp_path / "1")
            kr2, _ = self._keyring(tmp_path / "2")  # different random key
            a = await _mk_pool("a", keyring=kr1)
            b = await _mk_pool("b", keyring=kr2)
            assert await b.join([f"127.0.0.1:{a.local_addr[1]}"]) == 0
            await a.stop()
            await b.stop()
        loop.run_until_complete(body())


class TestSnapshots:
    def test_snapshot_and_previous_peers(self, loop, tmp_path):
        async def body():
            snap_a = str(tmp_path / "a" / "local.snapshot")
            a = await _mk_pool("a", snapshot_path=snap_a)
            seed = [f"127.0.0.1:{a.local_addr[1]}"]
            b = await _mk_pool("b", seeds=seed)
            assert await _wait(lambda: len(a.alive_members()) == 2)
            # a's snapshot eventually records b as a peer
            assert await _wait(
                lambda: any(str(b.local_addr[1]) in p
                            for p in SerfPool.previous_peers(snap_a)),
                timeout=10)
            await a.stop()
            await b.stop()
        loop.run_until_complete(body())


class TestMergeDelegates:
    """consul/merge.go: pools refuse members that don't belong."""

    def test_lan_pool_refuses_wrong_datacenter(self, loop):
        async def body():
            def dc1_only(node):
                return node.tags.get("dc", "dc1") == "dc1"

            a = SerfPool(_fast("a", server_tags("dc1", 8300)),
                         member_filter=dc1_only)
            await a.start()
            stranger = SerfPool(_fast("x", server_tags("dc2", 8300)))
            await stranger.start()
            # the stranger CAN push/pull with a, but a never admits it
            await stranger.join([f"127.0.0.1:{a.local_addr[1]}"])
            await asyncio.sleep(0.3)
            assert "x" not in {n.name for n in a.members()}, \
                "cross-DC member leaked past the LAN merge delegate"
            await stranger.stop()
            await a.stop()
        loop.run_until_complete(body())

    def test_wan_pool_refuses_non_servers(self, loop):
        async def body():
            def servers_only(node):
                return node.tags.get("role") == "consul"

            a = SerfPool(_fast("a.dc1", server_tags("dc1", 8300)),
                         member_filter=servers_only)
            await a.start()
            client = SerfPool(_fast("c1", client_tags("dc1")))
            await client.start()
            await client.join([f"127.0.0.1:{a.local_addr[1]}"])
            await asyncio.sleep(0.3)
            assert "c1" not in {n.name for n in a.members()}, \
                "client member leaked into the WAN pool"
            await client.stop()
            await a.stop()
        loop.run_until_complete(body())


class TestProtocolNegotiation:
    """Protocol version negotiation (consul/config.go:31-37, tags at
    consul/server.go:292-304): nodes advertise vsn/vsn_min/vsn_max and
    incompatible versions refuse to merge."""

    def test_incompatible_versions_refuse_to_merge(self, loop):
        async def body():
            # a speaks only version 2 ([2, 2]); x speaks only a future
            # version 9 ([9, 9]) — neither side can pick a common
            # protocol, so the join must not admit the peer.
            a = SerfPool(_fast(
                "a", {"role": "consul", "dc": "dc1", "port": "8300",
                      "vsn": "2", "vsn_min": "2", "vsn_max": "2"},
                protocol_version=2, protocol_min=2, protocol_max=2))
            await a.start()
            x = SerfPool(_fast(
                "x", {"role": "consul", "dc": "dc1", "port": "8300",
                      "vsn": "9", "vsn_min": "9", "vsn_max": "9"},
                protocol_version=9, protocol_min=9, protocol_max=9))
            await x.start()
            await x.join([f"127.0.0.1:{a.local_addr[1]}"])
            await asyncio.sleep(0.3)
            assert "x" not in {n.name for n in a.members()}, \
                "incompatible protocol version admitted"
            assert "a" not in {n.name for n in x.members()}, \
                "incompatible protocol version admitted (reverse)"
            await x.stop()
            await a.stop()
        loop.run_until_complete(body())

    def test_version_overlap_merges(self, loop):
        async def body():
            # a operates v1 of [1, 2]; b operates v2 of [1, 2]: each
            # side's operating version lies in the other's supported
            # range — a mid-rolling-upgrade cluster must stay merged.
            a = SerfPool(_fast(
                "a", {"role": "consul", "dc": "dc1", "port": "8300",
                      "vsn": "1", "vsn_min": "1", "vsn_max": "2"},
                protocol_version=1, protocol_min=1, protocol_max=2))
            await a.start()
            b = SerfPool(_fast(
                "b", {"role": "consul", "dc": "dc1", "port": "8300",
                      "vsn": "2", "vsn_min": "1", "vsn_max": "2"},
                protocol_version=2, protocol_min=1, protocol_max=2))
            await b.start()
            await b.join([f"127.0.0.1:{a.local_addr[1]}"])
            assert await _wait(
                lambda: {"a", "b"} <= {n.name for n in a.members()}
                and {"a", "b"} <= {n.name for n in b.members()})
            await b.stop()
            await a.stop()
        loop.run_until_complete(body())
