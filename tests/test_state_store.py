"""State store semantics, mirroring the reference's state_store_test.go
coverage tiers (SURVEY.md §4 tier 2 — pure-logic, no networking)."""

import threading

import pytest

from consul_tpu.state import StateStore, StateStoreError
from consul_tpu.state.tombstone_gc import TombstoneGC
from consul_tpu.structs.structs import (
    ACL, DirEntry, HEALTH_CRITICAL, HEALTH_PASSING, HealthCheck, NodeService,
    RegisterRequest, SESSION_BEHAVIOR_DELETE, Session)


def reg(store, index, node="node1", addr="10.0.0.1", service=None, check=None):
    store.ensure_registration(index, RegisterRequest(
        node=node, address=addr, service=service, check=check))


class TestCatalog:
    def test_node_register_and_list(self):
        s = StateStore()
        reg(s, 1, "n1", "10.0.0.1")
        reg(s, 2, "n2", "10.0.0.2")
        idx, nodes = s.nodes()
        assert idx == 2
        assert [n.node for n in nodes] == ["n1", "n2"]
        idx, addr = s.get_node("n1")
        assert addr == "10.0.0.1"

    def test_service_requires_node(self):
        s = StateStore()
        with pytest.raises(StateStoreError):
            s.ensure_service(1, "ghost", NodeService(id="a", service="a"))

    def test_service_nodes_and_tags(self):
        s = StateStore()
        reg(s, 1, "n1")
        reg(s, 2, "n2", "10.0.0.2")
        s.ensure_service(3, "n1", NodeService(id="web", service="web", tags=["v1"], port=80))
        s.ensure_service(4, "n2", NodeService(id="web", service="web", tags=["v2"], port=81))
        idx, sns = s.service_nodes("web")
        assert idx == 4 and len(sns) == 2
        assert sns[0].address == "10.0.0.1"
        _, tagged = s.service_nodes("web", tag="v2")
        assert [sn.node for sn in tagged] == ["n2"]
        _, services = s.services()
        assert services == {"web": ["v1", "v2"]}

    def test_check_defaults_critical_and_joins(self):
        s = StateStore()
        reg(s, 1, "n1")
        s.ensure_service(2, "n1", NodeService(id="web", service="web"))
        s.ensure_check(3, HealthCheck(node="n1", check_id="c1", service_id="web", status=""))
        idx, checks = s.node_checks("n1")
        assert checks[0].status == HEALTH_CRITICAL
        assert checks[0].service_name == "web"
        # node-level check joins into check_service_nodes
        s.ensure_check(4, HealthCheck(node="n1", check_id="serfHealth",
                                      status=HEALTH_PASSING))
        _, csns = s.check_service_nodes("web")
        assert len(csns) == 1
        assert {c.check_id for c in csns[0].checks} == {"c1", "serfHealth"}

    def test_delete_node_cascades(self):
        s = StateStore()
        reg(s, 1, "n1")
        s.ensure_service(2, "n1", NodeService(id="web", service="web"))
        s.ensure_check(3, HealthCheck(node="n1", check_id="c1", status=HEALTH_PASSING))
        s.delete_node(4, "n1")
        assert s.nodes()[1] == []
        assert s.service_nodes("web")[1] == []
        assert s.node_checks("n1")[1] == []

    def test_registration_is_atomic_on_invalid_check(self):
        # A check naming an unknown service must leave NO partial state
        # (reference: aborting LMDB txn, state_store.go:499-534).
        s = StateStore()
        with pytest.raises(StateStoreError):
            s.ensure_registration(1, RegisterRequest(
                node="n1", address="10.0.0.1",
                service=NodeService(id="web", service="web"),
                check=HealthCheck(node="n1", check_id="c1", service_id="ghost")))
        assert s.nodes()[1] == []
        assert s.service_nodes("web")[1] == []
        assert s.last_index("nodes", "services", "checks") == 0

    def test_reads_return_copies(self):
        s = StateStore()
        reg(s, 1, "n1")
        s.kvs_set(2, DirEntry(key="k", value=b"v"))
        _, ent = s.kvs_get("k")
        ent.value = b"mutated"
        assert s.kvs_get("k")[1].value == b"v"
        s.ensure_check(3, HealthCheck(node="n1", check_id="c1", status=HEALTH_PASSING))
        _, checks = s.node_checks("n1")
        checks[0].status = "critical"
        assert s.node_checks("n1")[1][0].status == HEALTH_PASSING

    def test_node_dump(self):
        s = StateStore()
        reg(s, 1, "n1")
        s.ensure_service(2, "n1", NodeService(id="web", service="web"))
        _, dump = s.node_dump()
        assert dump[0]["node"] == "n1"
        assert dump[0]["services"][0].id == "web"


class TestKVS:
    def test_set_get_indexes(self):
        s = StateStore()
        s.kvs_set(5, DirEntry(key="foo", value=b"bar"))
        idx, ent = s.kvs_get("foo")
        assert idx == 5 and ent.create_index == 5 and ent.modify_index == 5
        s.kvs_set(7, DirEntry(key="foo", value=b"baz"))
        _, ent = s.kvs_get("foo")
        assert ent.create_index == 5 and ent.modify_index == 7

    def test_cas_semantics(self):
        s = StateStore()
        # modify_index=0 -> set-if-not-exists
        assert s.kvs_check_and_set(1, DirEntry(key="k", value=b"1", modify_index=0))
        assert not s.kvs_check_and_set(2, DirEntry(key="k", value=b"2", modify_index=0))
        # wrong index fails, right index wins
        assert not s.kvs_check_and_set(3, DirEntry(key="k", value=b"3", modify_index=99))
        assert s.kvs_check_and_set(4, DirEntry(key="k", value=b"4", modify_index=1))
        _, ent = s.kvs_get("k")
        assert ent.value == b"4"

    def test_list_and_list_keys(self):
        s = StateStore()
        for i, k in enumerate(["web/a", "web/b/c", "web/b/d", "other"], start=1):
            s.kvs_set(i, DirEntry(key=k, value=b"x"))
        _, idx, ents = s.kvs_list("web/")
        assert idx == 4
        assert [e.key for e in ents] == ["web/a", "web/b/c", "web/b/d"]
        _, keys = s.kvs_list_keys("web/", "/")
        assert keys == ["web/a", "web/b/"]
        _, keys = s.kvs_list_keys("", "/")
        assert keys == ["other", "web/"]

    def test_delete_tombstone_advances_list_index(self):
        s = StateStore()
        s.kvs_set(1, DirEntry(key="web/a", value=b"x"))
        s.kvs_set(2, DirEntry(key="web/b", value=b"x"))
        s.kvs_delete(3, "web/b")
        tomb_idx, idx, ents = s.kvs_list("web/")
        assert [e.key for e in ents] == ["web/a"]
        assert tomb_idx == 3 and idx == 3
        s.reap_tombstones(3)
        tomb_idx, _, _ = s.kvs_list("web/")
        assert tomb_idx == 0

    def test_delete_tree(self):
        s = StateStore()
        for i, k in enumerate(["a/1", "a/2", "b/1"], start=1):
            s.kvs_set(i, DirEntry(key=k, value=b"x"))
        s.kvs_delete_tree(5, "a/")
        _, _, ents = s.kvs_list("")
        assert [e.key for e in ents] == ["b/1"]
        tomb_idx, _, _ = s.kvs_list("a/")
        assert tomb_idx == 5

    def test_prefix_scan_handles_astral_keys(self):
        s = StateStore()
        s.kvs_set(1, DirEntry(key="web/\U0001F600x", value=b"x"))
        s.kvs_set(2, DirEntry(key="web/a", value=b"x"))
        _, _, ents = s.kvs_list("web/")
        assert [e.key for e in ents] == ["web/a", "web/\U0001F600x"]
        s.kvs_delete_tree(3, "web/")
        assert s.kvs_list("")[2] == []

    def test_delete_cas(self):
        s = StateStore()
        s.kvs_set(1, DirEntry(key="k", value=b"x"))
        assert not s.kvs_delete_check_and_set(2, "k", 99)
        assert s.kvs_get("k")[1] is not None
        assert s.kvs_delete_check_and_set(3, "k", 1)
        assert s.kvs_get("k")[1] is None


def make_session_env(s: StateStore):
    reg(s, 1, "n1")
    s.ensure_check(2, HealthCheck(node="n1", check_id="c1", status=HEALTH_PASSING))


class TestSessions:
    def test_create_validations(self):
        s = StateStore()
        make_session_env(s)
        with pytest.raises(StateStoreError):  # no node
            s.session_create(3, Session(id="s1", node="ghost"))
        with pytest.raises(StateStoreError):  # missing check
            s.session_create(3, Session(id="s1", node="n1", checks=["nope"]))
        s.ensure_check(3, HealthCheck(node="n1", check_id="crit", status=HEALTH_CRITICAL))
        with pytest.raises(StateStoreError):  # critical check
            s.session_create(4, Session(id="s1", node="n1", checks=["crit"]))
        s.session_create(5, Session(id="s1", node="n1", checks=["c1"]))
        _, sess = s.session_get("s1")
        assert sess.create_index == 5

    def test_lock_unlock(self):
        s = StateStore()
        make_session_env(s)
        s.session_create(3, Session(id="s1", node="n1"))
        s.session_create(4, Session(id="s2", node="n1"))
        assert s.kvs_lock(5, DirEntry(key="k", value=b"v", session="s1"))
        _, ent = s.kvs_get("k")
        assert ent.lock_index == 1 and ent.session == "s1"
        # second session cannot steal
        assert not s.kvs_lock(6, DirEntry(key="k", value=b"v", session="s2"))
        # wrong session cannot unlock
        assert not s.kvs_unlock(7, DirEntry(key="k", session="s2"))
        assert s.kvs_unlock(8, DirEntry(key="k", session="s1"))
        _, ent = s.kvs_get("k")
        assert ent.session == "" and ent.lock_index == 1
        # re-acquire bumps lock_index
        assert s.kvs_lock(9, DirEntry(key="k", value=b"v", session="s2"))
        assert s.kvs_get("k")[1].lock_index == 2

    def test_unlock_writes_new_value(self):
        # Reference kvsSet inserts the caller's entry on unlock — a
        # release-with-body updates the value (state_store.go:1540-1551).
        s = StateStore()
        make_session_env(s)
        s.session_create(3, Session(id="s1", node="n1"))
        s.kvs_lock(4, DirEntry(key="k", value=b"old", session="s1"))
        assert s.kvs_unlock(5, DirEntry(key="k", value=b"new", session="s1"))
        _, ent = s.kvs_get("k")
        assert ent.value == b"new" and ent.session == "" and ent.lock_index == 1

    def test_lock_requires_session(self):
        s = StateStore()
        with pytest.raises(StateStoreError):
            s.kvs_lock(1, DirEntry(key="k"))
        with pytest.raises(StateStoreError):
            s.kvs_lock(1, DirEntry(key="k", session="ghost"))

    def test_invalidation_releases_locks_with_delay(self):
        s = StateStore()
        make_session_env(s)
        s.session_create(3, Session(id="s1", node="n1", lock_delay=15.0))
        s.kvs_lock(4, DirEntry(key="k", value=b"v", session="s1"))
        s.session_destroy(5, "s1")
        assert s.session_get("s1")[1] is None
        _, ent = s.kvs_get("k")
        assert ent is not None and ent.session == "" and ent.modify_index == 5
        assert s.kvs_lock_delay("k") > 0

    def test_delete_behavior_deletes_keys(self):
        s = StateStore()
        make_session_env(s)
        s.session_create(3, Session(id="s1", node="n1",
                                    behavior=SESSION_BEHAVIOR_DELETE, lock_delay=0))
        s.kvs_lock(4, DirEntry(key="k", value=b"v", session="s1"))
        s.session_destroy(5, "s1")
        assert s.kvs_get("k")[1] is None
        tomb_idx, _, _ = s.kvs_list("k")
        assert tomb_idx == 5

    def test_critical_check_invalidates_session(self):
        s = StateStore()
        make_session_env(s)
        s.session_create(3, Session(id="s1", node="n1", checks=["c1"], lock_delay=0))
        s.kvs_lock(4, DirEntry(key="k", value=b"v", session="s1"))
        s.ensure_check(5, HealthCheck(node="n1", check_id="c1", status=HEALTH_CRITICAL))
        assert s.session_get("s1")[1] is None
        assert s.kvs_get("k")[1].session == ""

    def test_node_delete_invalidates_sessions(self):
        s = StateStore()
        make_session_env(s)
        s.session_create(3, Session(id="s1", node="n1"))
        s.delete_node(4, "n1")
        assert s.session_get("s1")[1] is None

    def test_node_sessions(self):
        s = StateStore()
        make_session_env(s)
        reg(s, 2, "n2")
        s.session_create(3, Session(id="s1", node="n1"))
        s.session_create(4, Session(id="s2", node="n2"))
        _, out = s.node_sessions("n1")
        assert [x.id for x in out] == ["s1"]


class TestACL:
    def test_set_get_delete(self):
        s = StateStore()
        s.acl_set(1, ACL(id="a1", name="x", rules="key \"\" { policy = \"read\" }"))
        idx, acl = s.acl_get("a1")
        assert idx == 1 and acl.create_index == 1
        s.acl_set(2, ACL(id="a1", name="y"))
        _, acl = s.acl_get("a1")
        assert acl.create_index == 1 and acl.modify_index == 2
        _, acls = s.acl_list()
        assert len(acls) == 1
        s.acl_delete(3, "a1")
        assert s.acl_get("a1")[1] is None


class TestWatches:
    def test_table_watch_fires_once(self):
        s = StateStore()
        ev = threading.Event()
        s.watch(s.query_tables("Nodes"), ev)
        reg(s, 1, "n1")
        assert ev.is_set()
        ev2 = threading.Event()
        reg(s, 2, "n2")  # not registered -> no cross-talk
        assert not ev2.is_set()

    def test_kv_prefix_watch(self):
        s = StateStore()
        ev = threading.Event()
        s.watch_kv("web/", ev)
        s.kvs_set(1, DirEntry(key="other", value=b"x"))
        assert not ev.is_set()
        s.kvs_set(2, DirEntry(key="web/a", value=b"x"))
        assert ev.is_set()

    def test_kv_root_watch_sees_everything(self):
        s = StateStore()
        ev = threading.Event()
        s.watch_kv("", ev)
        s.kvs_set(1, DirEntry(key="anything", value=b"x"))
        assert ev.is_set()

    def test_delete_tree_wakes_subtree_watchers(self):
        s = StateStore()
        s.kvs_set(1, DirEntry(key="a/b/c", value=b"x"))
        ev = threading.Event()
        s.watch_kv("a/b/", ev)
        s.kvs_delete_tree(2, "a/")
        assert ev.is_set()

    def test_stop_watch(self):
        s = StateStore()
        ev = threading.Event()
        s.watch_kv("k", ev)
        s.stop_watch_kv("k", ev)
        s.kvs_set(1, DirEntry(key="k", value=b"x"))
        assert not ev.is_set()


class TestTombstoneGC:
    def test_batching_and_collect(self):
        gc = TombstoneGC(ttl=10.0, granularity=5.0)
        gc.set_enabled(True, now=0.0)
        gc.hint(3, now=0.0)
        gc.hint(7, now=1.0)   # same bucket (expires ceil to 10 vs 15?)
        assert gc.pending_expiration()
        assert gc.collect(now=9.0) == []
        out = gc.collect(now=20.0)
        assert out and max(out) == 7
        assert not gc.pending_expiration()

    def test_disable_clears(self):
        gc = TombstoneGC(ttl=10.0, granularity=5.0)
        gc.set_enabled(True, now=0.0)
        gc.hint(3, now=0.0)
        gc.set_enabled(False, now=1.0)
        assert not gc.pending_expiration()
        gc.hint(9, now=2.0)  # disabled -> ignored
        assert not gc.pending_expiration()


class TestRadix:
    def test_walks(self):
        from consul_tpu.state.radix import RadixTree
        t = RadixTree()
        t.insert("", "root")
        t.insert("web/", "web")
        t.insert("web/a", "a")
        t.insert("wet", "wet")
        assert dict(t.walk_path("web/a/x")) == {"": "root", "web/": "web", "web/a": "a"}
        assert dict(t.walk_prefix("we")) == {"web/": "web", "web/a": "a", "wet": "wet"}
        assert t.longest_prefix("web/a/x") == ("web/a", "a")
        assert t.delete("web/")
        assert not t.delete("web/")
        assert t.get("web/a") == "a"
        assert len(t) == 3
