"""Watch framework tests (reference tier: watch/*_test.go against a
test agent)."""

import threading
import time

import pytest

from consul_tpu.api import Client, Config, KVPair
from consul_tpu.watch import parse
from consul_tpu.watch.plan import WatchError
from tests.test_agent_http import AgentHarness


@pytest.fixture(scope="module")
def harness():
    h = AgentHarness().start()
    yield h
    h.stop()


@pytest.fixture()
def addr(harness):
    host, port = harness.agent.http.addr
    return f"{host}:{port}"


@pytest.fixture()
def client(addr):
    c = Client(Config(address=addr))
    yield c
    c.close()


def _collect(plan, addr, n_events, timeout=10.0):
    """Run a plan in a thread; return the first n_events firings."""
    events = []
    got = threading.Event()

    def handler(index, result):
        events.append((index, result))
        if len(events) >= n_events:
            got.set()
            plan.stop()

    plan.handler = handler
    plan.run_in_thread(addr)
    got.wait(timeout)
    plan.stop()
    return events


class TestParse:
    def test_unknown_type(self):
        with pytest.raises(WatchError):
            parse({"type": "bogus"})

    def test_missing_type(self):
        with pytest.raises(WatchError):
            parse({})

    def test_missing_required(self):
        with pytest.raises(WatchError):
            parse({"type": "key"})

    def test_extra_params_rejected(self):
        with pytest.raises(WatchError):
            parse({"type": "key", "key": "a", "bogus": 1})

    def test_checks_exclusive(self):
        with pytest.raises(WatchError):
            parse({"type": "checks", "service": "a", "state": "passing"})

    def test_all_seven_types(self):
        for params in (
                {"type": "key", "key": "k"},
                {"type": "keyprefix", "prefix": "p/"},
                {"type": "services"},
                {"type": "nodes"},
                {"type": "service", "service": "web"},
                {"type": "checks", "state": "passing"},
                {"type": "event", "name": "deploy"},
        ):
            assert parse(params) is not None


class TestRun:
    def test_key_watch_fires_on_change(self, client, addr):
        client.kv.put(KVPair(key="w/key1", value=b"v0"))
        plan = parse({"type": "key", "key": "w/key1"})

        def writer():
            time.sleep(0.4)
            c = Client(Config(address=addr))
            c.kv.put(KVPair(key="w/key1", value=b"v1"))
            c.close()

        threading.Thread(target=writer, daemon=True).start()
        events = _collect(plan, addr, 2)
        assert len(events) >= 2
        assert events[0][1]["Value"] == b"v0"   # initial state
        assert events[1][1]["Value"] == b"v1"   # the change

    def test_keyprefix_watch(self, client, addr):
        plan = parse({"type": "keyprefix", "prefix": "w/tree/"})

        def writer():
            time.sleep(0.4)
            c = Client(Config(address=addr))
            c.kv.put(KVPair(key="w/tree/a", value=b"1"))
            c.close()

        threading.Thread(target=writer, daemon=True).start()
        events = _collect(plan, addr, 2)
        assert len(events) >= 2
        assert any(e["Key"] == "w/tree/a" for e in events[-1][1])

    def test_service_watch(self, client, addr):
        plan = parse({"type": "service", "service": "wsvc"})

        def register():
            time.sleep(0.4)
            c = Client(Config(address=addr))
            c.agent.service_register({"ID": "wsvc", "Name": "wsvc", "Port": 1})
            c.close()

        threading.Thread(target=register, daemon=True).start()
        events = _collect(plan, addr, 2)
        assert events[0][1] == []  # before registration
        assert any(e["Service"]["ID"] == "wsvc" for e in events[-1][1])
        client.agent.service_deregister("wsvc")

    def test_checks_state_watch(self, client, addr):
        plan = parse({"type": "checks", "state": "warning"})

        def register():
            time.sleep(0.4)
            c = Client(Config(address=addr))
            c.agent.check_register({"Name": "wchk", "TTL": "30s"})
            c.warn_ttl = c.agent.warn_ttl("wchk", note="careful")
            c.close()

        threading.Thread(target=register, daemon=True).start()
        events = _collect(plan, addr, 2)
        assert any(ch["CheckID"] == "wchk" for ch in events[-1][1])
        client.agent.check_deregister("wchk")

    def test_shell_handler(self, client, addr, tmp_path):
        out_file = tmp_path / "fired"
        plan = parse({
            "type": "key", "key": "w/handler",
            "handler": f'cat > {out_file}; echo "$CONSUL_INDEX" >> {out_file}'})
        client.kv.put(KVPair(key="w/handler", value=b"x"))
        plan.run_in_thread(addr)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not out_file.exists():
            time.sleep(0.1)
        plan.stop()
        assert out_file.exists()
        content = out_file.read_text()
        assert '"Key": "w/handler"' in content
        # CONSUL_INDEX env appended as the last line
        assert int(content.strip().rsplit("\n", 1)[-1]) > 0
