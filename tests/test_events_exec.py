"""User events + remote exec tests (reference tier:
command/agent/user_event_test.go, remote_exec_test.go, exec e2e)."""

import threading
import time

import pytest

from consul_tpu.api import Client, Config
from consul_tpu.api.exec import ExecJob
from tests.test_agent_http import AgentHarness


@pytest.fixture(scope="module")
def harness():
    h = AgentHarness().start()
    yield h
    h.stop()


@pytest.fixture()
def client(harness):
    host, port = harness.agent.http.addr
    c = Client(Config(address=f"{host}:{port}"))
    yield c
    c.close()


class TestUserEvents:
    def test_fire_and_list(self, client):
        eid = client.event.fire("deploy", payload=b"v1.2.3")
        assert eid
        events, meta = client.event.list()
        assert any(e["ID"] == eid for e in events)
        assert meta.last_index > 0
        got = [e for e in events if e["ID"] == eid][0]
        assert got["Name"] == "deploy"
        import base64
        assert base64.b64decode(got["Payload"]) == b"v1.2.3"
        assert got["LTime"] > 0

    def test_name_filter_in_list(self, client):
        client.event.fire("alpha")
        client.event.fire("beta")
        events, _ = client.event.list("alpha")
        assert events and all(e["Name"] == "alpha" for e in events)

    def test_node_filter_drops_event(self, client):
        # our node is node1; a filter for another node must not be ingested
        client.event.fire("targeted", node_filter="^other-node$")
        events, _ = client.event.list("targeted")
        assert events == []
        # matching filter is delivered
        client.event.fire("targeted2", node_filter="^node1$")
        events, _ = client.event.list("targeted2")
        assert len(events) == 1

    def test_service_filter(self, client, harness):
        client.agent.service_register({"ID": "evsvc", "Name": "evsvc",
                                       "Port": 1, "Tags": ["blue"]})
        client.event.fire("svc-ev", service_filter="^evsvc$")
        events, _ = client.event.list("svc-ev")
        assert len(events) == 1
        # tag filter mismatch drops
        client.event.fire("svc-ev-tag", service_filter="^evsvc$",
                          tag_filter="^green$")
        events, _ = client.event.list("svc-ev-tag")
        assert events == []
        client.event.fire("svc-ev-tag2", service_filter="^evsvc$",
                          tag_filter="^blue$")
        events, _ = client.event.list("svc-ev-tag2")
        assert len(events) == 1
        client.agent.service_deregister("evsvc")

    def test_tag_without_service_rejected(self, client):
        from consul_tpu.api import APIError
        with pytest.raises(APIError) as ei:
            client.event.fire("bad", tag_filter="x")
        assert ei.value.status == 400

    def test_blocking_list(self, client):
        events, meta = client.event.list()
        idx = meta.last_index

        def firer():
            time.sleep(0.3)
            c2 = Client(Config(address=client.config.address))
            c2.event.fire("wakeup")
            c2.close()

        threading.Thread(target=firer, daemon=True).start()
        t0 = time.monotonic()
        from consul_tpu.api.client import QueryOptions
        events, meta2 = client.event.list(q=QueryOptions(
            wait_index=idx, wait_time=10.0))
        assert time.monotonic() - t0 < 5.0
        assert meta2.last_index > idx


class TestRemoteExec:
    def test_exec_roundtrip(self, client):
        job = ExecJob(client, "echo exec-says-hi", wait=15.0)
        result = job.run()
        assert result.acks == ["node1"]
        assert result.exits == {"node1": 0}
        assert b"exec-says-hi" in result.outputs.get("node1", b"")

    def test_exec_exit_code(self, client):
        job = ExecJob(client, "exit 3", wait=15.0)
        result = job.run()
        assert result.exits == {"node1": 3}

    def test_exec_node_filter_excludes(self, client):
        job = ExecJob(client, "echo hi", node_filter="^not-us$", wait=3.0)
        result = job.run()
        assert result.acks == [] and result.exits == {}

    def test_rexec_not_in_event_ring(self, client):
        """_rexec events are intercepted, never listed (user_event.go)."""
        ExecJob(client, "true", wait=10.0).run()
        events, _ = client.event.list("_rexec")
        assert events == []
