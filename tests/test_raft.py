"""Raft consensus tests — compressed-timer in-process clusters.

Mirrors the reference's test shape (SURVEY.md §4: multi-node simulated
in one process with accelerated protocol timers, consul/server_test.go:
64-69 uses 40ms raft heartbeats; here 20ms) and its assertion style
(WaitForResult polling, testutil/wait.go:12-28).
"""

from __future__ import annotations

import asyncio

import msgpack
import pytest

from consul_tpu.consensus.log import FileLogStore
from consul_tpu.consensus.raft import (
    MemoryTransport, NotLeaderError, RaftConfig, RaftNode)
from consul_tpu.consensus.snapshot import FileSnapshotStore


def fast_config(**kw) -> RaftConfig:
    base = dict(heartbeat_interval=0.02, election_timeout_min=0.06,
                election_timeout_max=0.12, rpc_timeout=0.05,
                snapshot_threshold=10_000, trailing_logs=16)
    base.update(kw)
    return RaftConfig(**base)


class KVFSM:
    """Tiny log-appending FSM: entries are msgpack [key, value]."""

    def __init__(self) -> None:
        self.data = {}
        self.applied = []

    def apply(self, index, buf):
        k, v = msgpack.unpackb(buf, raw=False)
        self.data[k] = v
        self.applied.append(index)
        return v

    def snapshot(self, last_index):
        return msgpack.packb([last_index, self.data], use_bin_type=True)

    def restore(self, buf):
        last_index, self.data = msgpack.unpackb(buf, raw=False)
        self.applied = []
        return last_index


def make_cluster(n, transport=None, config=None, stores=None, snaps=None):
    transport = transport or MemoryTransport()
    ids = [f"s{i}" for i in range(n)]
    nodes = []
    for i, nid in enumerate(ids):
        node = RaftNode(
            nid, ids, KVFSM(), transport, config or fast_config(),
            log_store=stores[i] if stores else None,
            snap_store=snaps[i] if snaps else None)
        nodes.append(node)
    return transport, nodes


async def wait_for_leader(nodes, timeout=5.0):
    """Poll until exactly one live node leads (testutil/wait.go shape)."""
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        leaders = [x for x in nodes if x.is_leader()]
        if len(leaders) == 1:
            return leaders[0]
        await asyncio.sleep(0.01)
    raise AssertionError(
        f"no single leader: {[(x.id, x.role, x.current_term) for x in nodes]}")


async def wait_until(pred, timeout=5.0, msg="condition"):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if pred():
            return
        await asyncio.sleep(0.01)
    raise AssertionError(f"timeout waiting for {msg}")


async def start_all(nodes):
    for x in nodes:
        x.start()


async def stop_all(nodes):
    for x in nodes:
        await x.shutdown()


def put(k, v):
    return msgpack.packb([k, v], use_bin_type=True)


def test_single_node_bootstrap():
    async def main():
        _, nodes = make_cluster(1)
        await start_all(nodes)
        leader = await wait_for_leader(nodes)
        out = await leader.apply(put("a", 1))
        assert out == 1
        assert leader.fsm.data == {"a": 1}
        await leader.barrier()
        await stop_all(nodes)
    asyncio.run(main())


def test_three_node_election_and_replication():
    async def main():
        _, nodes = make_cluster(3)
        await start_all(nodes)
        leader = await wait_for_leader(nodes)
        for i in range(5):
            await leader.apply(put(f"k{i}", i))
        await wait_until(
            lambda: all(x.fsm.data == {f"k{i}": i for i in range(5)}
                        for x in nodes),
            msg="fsm convergence")
        # Followers reject client writes.
        follower = next(x for x in nodes if not x.is_leader())
        with pytest.raises(NotLeaderError):
            await follower.apply(put("x", 1))
        await stop_all(nodes)
    asyncio.run(main())


def test_leader_failover_preserves_log():
    async def main():
        _, nodes = make_cluster(3)
        await start_all(nodes)
        leader = await wait_for_leader(nodes)
        await leader.apply(put("before", 1))
        await leader.shutdown()
        rest = [x for x in nodes if x is not leader]
        new_leader = await wait_for_leader(rest)
        assert new_leader is not leader
        await new_leader.apply(put("after", 2))
        await wait_until(
            lambda: all(x.fsm.data == {"before": 1, "after": 2} for x in rest),
            msg="post-failover convergence")
        await stop_all(rest)
    asyncio.run(main())


def test_partitioned_leader_steps_down_no_split_brain():
    async def main():
        tr, nodes = make_cluster(3)
        await start_all(nodes)
        leader = await wait_for_leader(nodes)
        await leader.apply(put("pre", 1))
        tr.isolate(leader.id)
        rest = [x for x in nodes if x is not leader]
        new_leader = await wait_for_leader(rest)
        await new_leader.apply(put("maj", 2))
        # Minority leader cannot commit.
        with pytest.raises((NotLeaderError, asyncio.TimeoutError)):
            await leader.apply(put("min", 3), timeout=0.3)
        tr.rejoin(leader.id)
        # Old leader rejoins as follower and converges on the majority log.
        await wait_until(lambda: not leader.is_leader(), msg="step down")
        await wait_until(
            lambda: leader.fsm.data.get("maj") == 2
            and "min" not in new_leader.fsm.data,
            msg="heal convergence")
        await stop_all(nodes)
    asyncio.run(main())


def test_snapshot_compaction_and_catchup():
    async def main():
        cfg = fast_config(snapshot_threshold=20, trailing_logs=4)
        _, nodes = make_cluster(3, config=cfg)
        await start_all(nodes)
        leader = await wait_for_leader(nodes)
        for i in range(40):
            await leader.apply(put(f"k{i}", i))
        await wait_until(lambda: leader._snap_index > 0, msg="snapshot taken")
        assert leader.log.first_index() > 1  # compacted
        await stop_all(nodes)
    asyncio.run(main())


def test_new_peer_joins_via_snapshot():
    async def main():
        cfg = fast_config(snapshot_threshold=15, trailing_logs=2)
        tr, nodes = make_cluster(3, config=cfg)
        await start_all(nodes)
        leader = await wait_for_leader(nodes)
        for i in range(30):
            await leader.apply(put(f"k{i}", i))
        await wait_until(lambda: leader._snap_index > 0, msg="snapshot")
        joiner = RaftNode("s3", [], KVFSM(), tr, cfg)
        joiner.start()
        await leader.add_peer("s3")
        await wait_until(
            lambda: len(joiner.fsm.data) + joiner._snap_index >= 30
            and joiner.last_applied >= 30,
            msg="joiner catch-up")
        assert joiner.fsm.data.get("k29") == 29
        await stop_all(nodes + [joiner])
    asyncio.run(main())


def test_remove_peer_shrinks_quorum():
    async def main():
        _, nodes = make_cluster(3)
        await start_all(nodes)
        leader = await wait_for_leader(nodes)
        victim = next(x for x in nodes if not x.is_leader())
        await leader.remove_peer(victim.id)
        await victim.shutdown()
        # 2-node cluster still commits (quorum 2 of 2).
        await leader.apply(put("post-remove", 1))
        assert leader.fsm.data["post-remove"] == 1
        await stop_all([x for x in nodes if x is not victim])
    asyncio.run(main())


def test_file_log_store_persistence(tmp_path):
    async def main():
        store = FileLogStore(str(tmp_path / "raft"))
        snaps = FileSnapshotStore(str(tmp_path / "snaps"))
        node = RaftNode("s0", ["s0"], KVFSM(), MemoryTransport(),
                        fast_config(), log_store=store, snap_store=snaps)
        node.start()
        await wait_for_leader([node])
        for i in range(10):
            await node.apply(put(f"k{i}", i))
        node.take_snapshot()
        for i in range(10, 15):
            await node.apply(put(f"k{i}", i))
        term = node.current_term
        await node.shutdown()

        # Restart from disk: snapshot restores, tail of log replays.
        store2 = FileLogStore(str(tmp_path / "raft"))
        snaps2 = FileSnapshotStore(str(tmp_path / "snaps"))
        node2 = RaftNode("s0", ["s0"], KVFSM(), MemoryTransport(),
                         fast_config(), log_store=store2, snap_store=snaps2)
        assert node2.current_term == term  # stable store survived
        node2.start()
        await wait_for_leader([node2])
        await node2.barrier()  # commits the restart no-op, replaying the log
        assert node2.fsm.data == {f"k{i}": i for i in range(15)}
        await node2.shutdown()
    asyncio.run(main())


def test_file_log_store_torn_tail(tmp_path):
    store = FileLogStore(str(tmp_path / "raft"))
    from consul_tpu.consensus.log import LogEntry
    store.append([LogEntry(1, 1, 0, b"good")])
    store.append([LogEntry(2, 1, 0, b"also-good")])
    store.close()
    # Corrupt the tail: truncate mid-record.
    seg = tmp_path / "raft" / "log.seg"
    data = seg.read_bytes()
    seg.write_bytes(data[:-3])
    store2 = FileLogStore(str(tmp_path / "raft"))
    assert store2.last_index() == 1
    assert store2.get(1).data == b"good"
    store2.close()
