"""RPC mesh tests: multi-server TCP clusters, forwarding, cross-DC,
TLS, keyring (reference tier: consul/server_test.go multi-server +
consul/rpc.go forwarding paths, all on loopback with compressed
timers per SURVEY §4)."""

import asyncio
import base64
import os
import subprocess

import pytest

from consul_tpu.consensus.raft import RaftConfig
from consul_tpu.server.server import Server, ServerConfig
from consul_tpu.structs.structs import (
    DirEntry, KVSOp, KVSRequest, KeyRequest, NodeService, RegisterRequest)

FAST = RaftConfig(heartbeat_interval=0.03, election_timeout_min=0.06,
                  election_timeout_max=0.12, rpc_timeout=0.5)


@pytest.fixture()
def loop():
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    yield loop
    loop.close()


async def _mk_cluster(n=3, dc="dc1", name_prefix="s", acl_dc=""):
    """N servers over real TCP on loopback (testServerConfig shape)."""
    names = [f"{name_prefix}{i}" for i in range(1, n + 1)]
    servers = []
    for name in names:
        srv = Server(ServerConfig(node_name=name, datacenter=dc,
                                  bootstrap=(n == 1), peers=list(names),
                                  raft=FAST, acl_datacenter=acl_dc,
                                  acl_default_policy="deny",
                                  acl_master_token="root" if acl_dc else ""))
        addr = await srv.attach_rpc("127.0.0.1", 0)
        servers.append((srv, f"{addr[0]}:{addr[1]}"))
    for srv, _ in servers:
        for other, addr in servers:
            srv.set_route(other.config.node_name, addr)
    for srv, _ in servers:
        await srv.start()
    await servers[0][0].wait_for_leader()
    return servers


async def _shutdown(servers):
    for srv, _ in servers:
        await srv.stop()


class TestTCPCluster:
    def test_three_server_election_and_replication(self, loop):
        async def body():
            servers = await _mk_cluster(3)
            leaders = {srv.raft.leader_id for srv, _ in servers}
            assert len(leaders) == 1 and None not in leaders
            leader = next(srv for srv, _ in servers if srv.is_leader())
            await leader.kvs.apply(KVSRequest(
                op=KVSOp.SET.value, dir_ent=DirEntry(key="k", value=b"v")))
            # replicated to every FSM
            for srv, _ in servers:
                deadline = asyncio.get_event_loop().time() + 5
                while asyncio.get_event_loop().time() < deadline:
                    _, ent = srv.store.kvs_get("k")
                    if ent is not None:
                        break
                    await asyncio.sleep(0.02)
                assert ent is not None and ent.value == b"v"
            await _shutdown(servers)

        loop.run_until_complete(body())

    def test_follower_write_forwards_to_leader(self, loop):
        async def body():
            servers = await _mk_cluster(3)
            follower = next(srv for srv, _ in servers if not srv.is_leader())
            # the follower's own endpoint path: raft_apply hops to leader
            ok = await follower.kvs.apply(KVSRequest(
                op=KVSOp.SET.value,
                dir_ent=DirEntry(key="fwd", value=b"from-follower")))
            assert ok
            leader = next(srv for srv, _ in servers if srv.is_leader())
            deadline = asyncio.get_event_loop().time() + 5
            while asyncio.get_event_loop().time() < deadline:
                _, ent = leader.store.kvs_get("fwd")
                if ent is not None:
                    break
                await asyncio.sleep(0.02)
            assert ent.value == b"from-follower"
            await _shutdown(servers)

        loop.run_until_complete(body())

    def test_rpc_read_on_follower_forwards(self, loop):
        async def body():
            servers = await _mk_cluster(3)
            leader = next(srv for srv, _ in servers if srv.is_leader())
            follower_addr = next(addr for srv, addr in servers
                                 if not srv.is_leader())
            await leader.kvs.apply(KVSRequest(
                op=KVSOp.SET.value, dir_ent=DirEntry(key="r", value=b"x")))
            # a default-consistency read sent to a follower's RPC port
            # hops to the leader (rpc.go:196-199)
            out = await leader.pool.rpc(follower_addr, "KVS.Get",
                                        {"key": "r", "opts": {}})
            assert out["data"][0]["value"] == b"x"
            assert out["meta"]["known_leader"] is True
            # stale read served locally by the follower — eventually
            # consistent by definition (QueryOptions.AllowStale,
            # consul/structs/structs.go:78-106), so poll for the apply
            # to land on the follower's FSM
            deadline = asyncio.get_event_loop().time() + 5
            while asyncio.get_event_loop().time() < deadline:
                out = await leader.pool.rpc(follower_addr, "KVS.Get",
                                            {"key": "r",
                                             "opts": {"allow_stale": True}})
                if out["data"]:
                    break
                await asyncio.sleep(0.02)
            assert out["data"], "stale read did not converge within 5s"
            assert out["data"][0]["value"] == b"x"
            await _shutdown(servers)

        loop.run_until_complete(body())

    def test_failover_reelection(self, loop):
        async def body():
            servers = await _mk_cluster(3)
            leader = next(srv for srv, _ in servers if srv.is_leader())
            rest = [srv for srv, _ in servers if srv is not leader]
            await leader.stop()
            deadline = asyncio.get_event_loop().time() + 10
            new_leader = None
            while asyncio.get_event_loop().time() < deadline:
                new_leader = next((s for s in rest if s.is_leader()), None)
                if new_leader is not None:
                    break
                await asyncio.sleep(0.05)
            assert new_leader is not None
            ok = await new_leader.kvs.apply(KVSRequest(
                op=KVSOp.SET.value, dir_ent=DirEntry(key="post", value=b"f")))
            assert ok
            for srv in rest:
                await srv.stop()

        loop.run_until_complete(body())


class TestCrossDC:
    def test_forward_dc_and_datacenters(self, loop):
        async def body():
            dc1 = await _mk_cluster(1, dc="dc1", name_prefix="a")
            dc2 = await _mk_cluster(1, dc="dc2", name_prefix="b")
            s1, addr1 = dc1[0]
            s2, addr2 = dc2[0]
            s1.set_remote_dc("dc2", [addr2])
            s2.set_remote_dc("dc1", [addr1])
            assert s1.known_datacenters() == ["dc1", "dc2"]

            # register a service in dc2, query it THROUGH dc1's server
            await s2.catalog.register(RegisterRequest(
                node="remote-node", address="10.2.0.1",
                service=NodeService(id="db", service="db", port=5432)))
            out = await s1.rpc_server._dispatch({
                "Method": "Catalog.ServiceNodes",
                "Body": {"service": "db",
                         "opts": {"datacenter": "dc2"}}})
            assert not out["Error"], out
            rows = out["Body"]["data"]
            assert rows and rows[0]["node"] == "remote-node"
            await _shutdown(dc1 + dc2)

        loop.run_until_complete(body())

    def test_acl_replication_from_auth_dc(self, loop):
        async def body():
            dc1 = await _mk_cluster(1, dc="dc1", name_prefix="a",
                                    acl_dc="dc1")
            dc2 = await _mk_cluster(1, dc="dc2", name_prefix="b",
                                    acl_dc="dc1")
            s1, addr1 = dc1[0]
            s2, addr2 = dc2[0]
            s2.set_remote_dc("dc1", [addr1])
            s1.set_remote_dc("dc2", [addr2])

            from consul_tpu.structs.structs import ACL, ACLOp, ACLRequest
            tok = await s1.acl.apply(ACLRequest(
                op=ACLOp.SET.value, token="root",
                acl=ACL(name="app", rules='key "app/" { policy = "write" }')))
            # dc2 resolves the token via ACL.GetPolicy to dc1
            acl = await s2.resolve_token(tok)
            assert acl is not None
            assert acl.key_write("app/x") and not acl.key_write("other")
            await _shutdown(dc1 + dc2)

        loop.run_until_complete(body())


class TestKeyring:
    def test_keyring_ops(self, tmp_path, loop):
        async def body():
            from consul_tpu.agent.keyring import Keyring, KeyringError
            k1 = base64.b64encode(os.urandom(16)).decode()
            k2 = base64.b64encode(os.urandom(16)).decode()
            ring = Keyring(path=str(tmp_path / "local.keyring"),
                           initial_key=k1)
            assert ring.primary == k1
            ring.install(k2)
            assert set(ring.list_keys()) == {k1, k2}
            with pytest.raises(KeyringError):
                ring.remove(k1)  # primary
            ring.use(k2)
            assert ring.primary == k2
            ring.remove(k1)
            assert ring.list_keys() == [k2]
            # persistence
            ring2 = Keyring(path=str(tmp_path / "local.keyring"))
            assert ring2.primary == k2
            with pytest.raises(KeyringError):
                ring.install("not-base64!")

        loop.run_until_complete(body())

    def test_agent_keyring_fanout(self, tmp_path, loop):
        async def body():
            from consul_tpu.agent.agent import Agent, AgentConfig
            key = base64.b64encode(os.urandom(16)).decode()
            agent = Agent(AgentConfig(http_port=0, dns_port=0,
                                      data_dir=str(tmp_path), encrypt=key))
            await agent.start()
            out = await agent.keyring_operation("list")
            assert out["Keys"] == {key: 1}
            k2 = base64.b64encode(os.urandom(16)).decode()
            await agent.keyring_operation("install", k2)
            out = await agent.keyring_operation("list")
            assert set(out["Keys"]) == {key, k2}
            await agent.stop()

        loop.run_until_complete(body())


def _make_certs(tmp_path):
    """Self-signed CA + server cert for server.dc1.consul via openssl."""
    ca_key = tmp_path / "ca.key"
    ca_crt = tmp_path / "ca.crt"
    sv_key = tmp_path / "sv.key"
    sv_csr = tmp_path / "sv.csr"
    sv_crt = tmp_path / "sv.crt"
    ext = tmp_path / "ext.cnf"
    ext.write_text("subjectAltName=DNS:server.dc1.consul\n")
    cmds = [
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "1",
         "-subj", "/CN=ConsulTestCA"],
        ["openssl", "req", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(sv_key), "-out", str(sv_csr),
         "-subj", "/CN=server.dc1.consul"],
        ["openssl", "x509", "-req", "-in", str(sv_csr), "-CA", str(ca_crt),
         "-CAkey", str(ca_key), "-CAcreateserial", "-out", str(sv_crt),
         "-days", "1", "-extfile", str(ext)],
    ]
    for cmd in cmds:
        proc = subprocess.run(cmd, capture_output=True)
        if proc.returncode != 0:
            pytest.skip(f"openssl unavailable/failed: {proc.stderr[:200]}")
    return str(ca_crt), str(sv_crt), str(sv_key)


class TestTLS:
    def test_tls_rpc_roundtrip(self, tmp_path, loop):
        async def body():
            from consul_tpu.tlsutil import TLSConfig
            ca, crt, key = _make_certs(tmp_path)
            tls = TLSConfig(verify_outgoing=True, ca_file=ca,
                            cert_file=crt, key_file=key, domain="consul.")
            srv = Server(ServerConfig(node_name="t1", raft=FAST))
            addr = await srv.attach_rpc(
                "127.0.0.1", 0, tls_incoming=tls.incoming_context(),
                tls_outgoing=tls.outgoing_wrapper())
            srv.set_route("t1", f"{addr[0]}:{addr[1]}")
            await srv.start()
            await srv.wait_for_leader()
            out = await srv.pool.rpc(f"{addr[0]}:{addr[1]}", "Status.Ping",
                                     {}, dc="dc1")
            assert out is True
            await srv.stop()

        loop.run_until_complete(body())

    def test_wrong_hostname_rejected(self, tmp_path, loop):
        async def body():
            from consul_tpu.rpc.pool import ConnPool
            from consul_tpu.tlsutil import TLSConfig
            ca, crt, key = _make_certs(tmp_path)
            tls = TLSConfig(verify_outgoing=True, ca_file=ca,
                            cert_file=crt, key_file=key, domain="consul.")
            srv = Server(ServerConfig(node_name="t1", raft=FAST))
            addr = await srv.attach_rpc("127.0.0.1", 0,
                                        tls_incoming=tls.incoming_context(),
                                        tls_outgoing=tls.outgoing_wrapper())
            await srv.start()
            await srv.wait_for_leader()
            # a pool verifying dc2's hostname must refuse dc1's cert
            pool = ConnPool(tls_wrap=tls.outgoing_wrapper())
            with pytest.raises(Exception):
                await pool.rpc(f"{addr[0]}:{addr[1]}", "Status.Ping", {},
                               dc="dc2", timeout=5.0)
            await pool.close()
            await srv.stop()

        loop.run_until_complete(body())


class TestFollowerConsistentReads:
    def test_consistent_read_served_by_follower(self, loop):
        """?consistent on a FOLLOWER's own endpoint path: the ReadIndex
        protocol (Raft §6.4) — leadership-verified commit index from
        the leader, local apply catch-up, local read.  The reference
        forwards the whole request (rpc.go:196-199); serving locally
        after the index round-trip is the same linearizability with
        less leader load.  Regression: this path used to raise
        NotLeaderError (http_bench's consistent leg ran 100% errors
        whenever the benched node was not the leader)."""
        async def body():
            servers = await _mk_cluster(3)
            leader = next(srv for srv, _ in servers if srv.is_leader())
            follower = next(srv for srv, _ in servers
                            if not srv.is_leader())
            await leader.kvs.apply(KVSRequest(
                op=KVSOp.SET.value, dir_ent=DirEntry(key="ci", value=b"1")))
            meta, ents = await follower.kvs.get(KeyRequest(
                key="ci", require_consistent=True))
            assert ents and ents[0].value == b"1"
            # linearizability across write-then-read: every write the
            # leader acked before the read began must be visible
            for i in range(5):
                await leader.kvs.apply(KVSRequest(
                    op=KVSOp.SET.value,
                    dir_ent=DirEntry(key="ci", value=b"%d" % i)))
                _, ents = await follower.kvs.get(KeyRequest(
                    key="ci", require_consistent=True))
                assert ents and ents[0].value == b"%d" % i, (i, ents)
            await _shutdown(servers)

        loop.run_until_complete(body())

    def test_read_index_is_leader_only(self, loop):
        """Server.ReadIndex on a non-leader fails loudly (no forwarding
        bounce between nodes that each think the other leads)."""
        async def body():
            servers = await _mk_cluster(3)
            leader = next(srv for srv, _ in servers if srv.is_leader())
            follower_addr = next(addr for srv, addr in servers
                                 if not srv.is_leader())
            from consul_tpu.rpc.pool import RPCError
            with pytest.raises(RPCError):
                await leader.pool.rpc(follower_addr, "Server.ReadIndex", {})
            # and on the leader it returns a committed index
            leader_addr = next(addr for srv, addr in servers
                               if srv.is_leader())
            out = await leader.pool.rpc(leader_addr, "Server.ReadIndex", {})
            assert out["index"] >= 1
            await _shutdown(servers)

        loop.run_until_complete(body())

    def test_ri_batching_never_joins_fired_confirmation(self, loop):
        """A read may only ride a ReadIndex confirmation whose index
        sample postdates its arrival: reads arriving while a batch's
        RPC is in flight form a NEW batch (two RPCs), while reads
        arriving before the batch fires share it (one RPC)."""
        async def body():
            servers = await _mk_cluster(3)
            follower = next(srv for srv, _ in servers
                            if not srv.is_leader())
            calls = []
            orig = follower.forward_leader

            async def slow(method, body):
                calls.append(method)
                await asyncio.sleep(0.15)
                return await orig(method, body)

            follower.forward_leader = slow
            # same-burst reads share one confirmation
            t1 = asyncio.ensure_future(follower.consistent_read_barrier())
            t2 = asyncio.ensure_future(follower.consistent_read_barrier())
            await asyncio.gather(t1, t2)
            assert len(calls) == 1, calls
            # a read arriving mid-flight gets its own (post-arrival) one
            calls.clear()
            t1 = asyncio.ensure_future(follower.consistent_read_barrier())
            await asyncio.sleep(0.05)   # batch 1 fired, RPC in flight
            t2 = asyncio.ensure_future(follower.consistent_read_barrier())
            await asyncio.gather(t1, t2)
            assert len(calls) == 2, calls
            await _shutdown(servers)

        loop.run_until_complete(body())
