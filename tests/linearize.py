"""Linearizability checker for single-register histories.

The Jepsen role in this tree (the reference documents its partition-
tolerance posture via Jepsen, ``website/source/docs/internals/
jepsen.html.markdown``; the actual Jepsen suite lives outside its repo).
This is the Wing & Gong search with the standard refinements Knossos/
Porcupine use: only *minimal* pending operations are candidates at each
step, and visited (linearized-set, model-state) pairs are memoized.

History entries are dicts:

    {"op": "w"|"r", "arg": v, "ret": v_or_None,
     "t_inv": float, "t_ret": float, "ok": bool}

``ok=False`` marks an operation whose outcome the client never learned
(timeout / connection lost mid-flight).  An unknown *write* may have
taken effect at any point after its invocation — or never; the checker
is free to linearize it anywhere after ``t_inv`` or to omit it.  An
unknown *read* constrains nothing and should simply not be recorded.

Checking is NP-complete in general; histories here are short (a few
hundred ops, concurrency ~4), where the minimal-op rule + memoization
make the search effectively linear in practice.
"""

from __future__ import annotations

import math
from typing import Dict, List


def check_linearizable(history: List[Dict], initial=None) -> bool:
    """True iff the register history has a linearization.

    Model: a single register.  ``w`` sets the value (any result), ``r``
    must return the model value at its linearization point (None = key
    absent, value ``initial`` before any write).
    """
    known: List[Dict] = []
    unknown: List[Dict] = []
    for e in history:
        if e.get("ok", True):
            known.append(e)
        elif e["op"] == "w":
            unknown.append({**e, "t_ret": math.inf})
        # unknown reads constrain nothing: drop

    ops = known + unknown
    n = len(ops)
    if n > 63:
        return _check_big(ops, len(known), initial)
    return _search(ops, len(known), initial)


def _search(ops, n_known, initial) -> bool:
    n = len(ops)
    full_known = 0
    for i in range(n_known):
        full_known |= 1 << i
    t_inv = [o["t_inv"] for o in ops]
    t_ret = [o["t_ret"] for o in ops]
    memo = set()

    def dfs(done: int, state) -> bool:
        if done & full_known == full_known:
            return True
        key = (done, state)
        if key in memo:
            return False
        # Minimal ops: invocation precedes every pending completion.
        min_ret = math.inf
        for i in range(n):
            if not (done >> i) & 1 and t_ret[i] < min_ret:
                min_ret = t_ret[i]
        for i in range(n):
            if (done >> i) & 1 or t_inv[i] > min_ret:
                continue
            o = ops[i]
            if o["op"] == "w":
                if dfs(done | (1 << i), o["arg"]):
                    return True
            else:  # known read: result must match the model
                if o["ret"] == state and dfs(done | (1 << i), state):
                    return True
        memo.add(key)
        return False

    return dfs(0, initial)


def _check_big(ops, n_known, initial) -> bool:
    """>63 ops: same search with frozenset masks (slower, no bit ops)."""
    t_ret = {id(o): o["t_ret"] for o in ops}
    known_ids = frozenset(id(o) for o in ops[:n_known])
    memo = set()

    def dfs(done: frozenset, state) -> bool:
        if known_ids <= done:
            return True
        key = (done, state)
        if key in memo:
            return False
        pending = [o for o in ops if id(o) not in done]
        min_ret = min(t_ret[id(o)] for o in pending)
        for o in pending:
            if o["t_inv"] > min_ret:
                continue
            if o["op"] == "w":
                if dfs(done | {id(o)}, o["arg"]):
                    return True
            elif o["ret"] == state and dfs(done | {id(o)}, state):
                return True
        memo.add(key)
        return False

    return dfs(frozenset(), initial)
