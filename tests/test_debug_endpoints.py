"""Profiling endpoints (/debug/pprof/* role, gated on enable_debug —
reference: command/agent/http.go:259-264 registers Go pprof only when
EnableDebug is set)."""

import httpx
import pytest

from consul_tpu.agent import AgentConfig

from test_agent_http import AgentHarness


@pytest.fixture(scope="module")
def debug_harness():
    h = AgentHarness(AgentConfig(http_port=0, dns_port=0,
                                 enable_debug=True)).start()
    yield h
    h.stop()


def test_debug_routes_absent_without_flag():
    h = AgentHarness().start()  # enable_debug defaults to False
    try:
        r = httpx.get(h.http_addr + "/debug/pprof/goroutine", timeout=5)
        assert r.status_code == 404
    finally:
        h.stop()


def test_goroutine_dump(debug_harness):
    r = httpx.get(debug_harness.http_addr + "/debug/pprof/goroutine",
                  timeout=5)
    assert r.status_code == 200
    # The dump must include real thread stacks and the agent's tasks.
    assert "threads" in r.text and "asyncio tasks" in r.text
    assert "-- thread" in r.text


def test_cpu_profile(debug_harness):
    r = httpx.get(debug_harness.http_addr
                  + "/debug/pprof/profile?seconds=0.2", timeout=10)
    assert r.status_code == 200
    assert "cpu profile" in r.text
    assert "cumulative" in r.text  # pstats table rendered


def test_heap_profile(debug_harness):
    r = httpx.get(debug_harness.http_addr + "/debug/pprof/heap?seconds=0.2",
                  timeout=10)
    assert r.status_code == 200
    assert "top sites" in r.text and "growth over window" in r.text


def test_seconds_clamped(debug_harness):
    # Bogus/huge windows must not hang the endpoint: clamped to [0.1, 30]
    # (and "bogus" falls back to the default).
    r = httpx.get(debug_harness.http_addr
                  + "/debug/pprof/profile?seconds=bogus", timeout=10)
    assert r.status_code == 200
