"""Profiling endpoints (/debug/pprof/* role, gated on enable_debug —
reference: command/agent/http.go:259-264 registers Go pprof only when
EnableDebug is set)."""

import httpx
import pytest

from consul_tpu.agent import AgentConfig

from test_agent_http import AgentHarness


@pytest.fixture(scope="module")
def debug_harness():
    h = AgentHarness(AgentConfig(http_port=0, dns_port=0,
                                 enable_debug=True)).start()
    yield h
    h.stop()


def test_debug_routes_absent_without_flag():
    h = AgentHarness().start()  # enable_debug defaults to False
    try:
        for path in ("/debug/pprof/goroutine", "/v1/agent/traces",
                     "/v1/agent/flight"):
            r = httpx.get(h.http_addr + path, timeout=5)
            assert r.status_code == 404, path
    finally:
        h.stop()


def test_goroutine_dump(debug_harness):
    r = httpx.get(debug_harness.http_addr + "/debug/pprof/goroutine",
                  timeout=5)
    assert r.status_code == 200
    # The dump must include real thread stacks and the agent's tasks.
    assert "threads" in r.text and "asyncio tasks" in r.text
    assert "-- thread" in r.text


def test_cpu_profile(debug_harness):
    r = httpx.get(debug_harness.http_addr
                  + "/debug/pprof/profile?seconds=0.2", timeout=10)
    assert r.status_code == 200
    assert "cpu profile" in r.text
    assert "cumulative" in r.text  # pstats table rendered


def test_heap_profile(debug_harness):
    r = httpx.get(debug_harness.http_addr + "/debug/pprof/heap?seconds=0.2",
                  timeout=10)
    assert r.status_code == 200
    assert "top sites" in r.text and "growth over window" in r.text


def test_seconds_clamped(debug_harness):
    # Bogus/huge windows must not hang the endpoint: clamped to [0.1, 30]
    # (and "bogus" falls back to the default).
    r = httpx.get(debug_harness.http_addr
                  + "/debug/pprof/profile?seconds=bogus", timeout=10)
    assert r.status_code == 200


def test_traces_endpoint_serves_request_trace(debug_harness):
    """Any traced HTTP request through the agent lands in the ring and
    comes back from /v1/agent/traces with its span tree."""
    from consul_tpu.obs.trace import tracer
    tracer.clear()
    r = httpx.put(debug_harness.http_addr + "/v1/kv/obs/probe",
                  content=b"x", timeout=10)
    assert r.status_code == 200
    r = httpx.get(debug_harness.http_addr + "/v1/agent/traces?limit=10",
                  timeout=5)
    assert r.status_code == 200
    traces = r.json()
    kv_traces = [t for t in traces
                 if any(s["Name"] == "http:kvs" for s in t["Spans"])]
    assert kv_traces, [t["Spans"][0]["Name"] for t in traces]
    spans = kv_traces[0]["Spans"]
    assert {s["TraceID"] for s in spans} == {kv_traces[0]["TraceID"]}
    names = {s["Name"] for s in spans}
    # single in-process server: http root + raft apply/commit + fsm
    assert {"http:kvs", "raft-apply", "raft-commit", "fsm:kvs"} <= names
    root = [s for s in spans if s["ParentID"] is None]
    assert len(root) == 1 and root[0]["Name"] == "http:kvs"


def test_flight_endpoint_degrades_without_kernel(debug_harness):
    """Asyncio gossip backend: the endpoint answers with an empty
    timeline instead of 500 (the recorder lives in the TPU plane)."""
    r = httpx.get(debug_harness.http_addr + "/v1/agent/flight", timeout=5)
    assert r.status_code == 200
    body = r.json()
    assert body["rows"] == [] and body["cols"] == []
    assert "backend" in body


def test_metrics_prometheus_format(debug_harness):
    """?format=prometheus returns the text exposition; default stays
    JSON.  (Not debug-gated — but the harness has traffic to render.)"""
    r = httpx.get(debug_harness.http_addr
                  + "/v1/agent/metrics?format=prometheus", timeout=5)
    assert r.status_code == 200
    assert r.headers["content-type"].startswith("text/plain")
    assert "# TYPE" in r.text
    r2 = httpx.get(debug_harness.http_addr + "/v1/agent/metrics", timeout=5)
    assert isinstance(r2.json(), list)
