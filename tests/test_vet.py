"""tools/vet — the eighteen-pass static analyzer + dynamic harness.

Each pass gets one known-bad snippet (the planted defect it must
catch) and one clean snippet (the idiomatic fix it must NOT flag),
plus the suppression machinery (``# noqa: CODE``, blanket ``# noqa``,
baseline), the exit-code contract, and the ``--format json`` /
``--report`` / ``--fast`` CI surface.  The meta-test at the bottom
holds the analyzer to its own standard.
"""

import asyncio
import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from tools.vet import async_safety, cancel_safety, carry_contract
from tools.vet import donation, exceptions
from tools.vet import fork_safety, interleave, names, overflow
from tools.vet import pallas_safety, role_transition, shard_exact
from tools.vet import table_drift, tracer_purity, wire_schema
from tools.vet import dyn as vet_dyn
from tools.vet.core import FileCtx, parse_noqa
from tools.vet.driver import ROLE_TRANSITION_GROUP, changed_paths
from tools.vet.driver import expand_partners
from tools.vet.driver import main as vet_main
from tools.vet.driver import prior_total_ms, run_vet, slowest_passes
from tools.vet.driver import time_guard_exceeded

REPO = Path(__file__).resolve().parent.parent


def _ctx(tmp_path, name, src):
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return FileCtx.load(p, p.as_posix())


def _codes(findings):
    return [f.code for f in findings]


# -- names (the legacy pyvet passes on the new walker) -----------------------


class TestNames:
    def test_undefined_name(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            def f():
                return not_defined_anywhere
            """)
        assert "N01" in _codes(names.check(ctx))

    def test_unused_import(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import os
            import sys

            print(sys.argv)
            """)
        found = names.check(ctx)
        assert _codes(found) == ["N02"]
        assert "os" in found[0].message

    def test_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import os

            def f():
                return os.getpid()
            """)
        assert names.check(ctx) == []


# -- async-safety ------------------------------------------------------------


class TestAsyncSafety:
    def test_a01_unawaited_coroutine(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            async def work():
                pass

            async def caller():
                work()
            """)
        assert "A01" in _codes(async_safety.check(ctx))

    def test_a01_self_method(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            class A:
                async def start(self):
                    pass

                def boot(self):
                    self.start()
            """)
        assert "A01" in _codes(async_safety.check(ctx))

    def test_a01_other_object_not_flagged(self, tmp_path):
        # self.local.start() must NOT match A.start — the sync method
        # of another object merely shares the name.
        ctx = _ctx(tmp_path, "m.py", """\
            class A:
                async def start(self):
                    pass

                def boot(self):
                    self.local.start()
            """)
        assert async_safety.check(ctx) == []

    def test_a02_dropped_task(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            async def main():
                asyncio.create_task(asyncio.sleep(1))
            """)
        assert "A02" in _codes(async_safety.check(ctx))

    def test_a02_task_set_pattern_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            tasks = set()

            async def main():
                t = asyncio.create_task(asyncio.sleep(1))
                tasks.add(t)
                t.add_done_callback(tasks.discard)
            """)
        assert async_safety.check(ctx) == []

    def test_a03_blocking_call(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import time

            async def f():
                time.sleep(1)
            """)
        assert "A03" in _codes(async_safety.check(ctx))

    def test_a03_through_from_import(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            from time import sleep

            async def f():
                sleep(1)
            """)
        assert "A03" in _codes(async_safety.check(ctx))

    def test_a03_nested_sync_def_clean(self, tmp_path):
        # a plain def nested in a coroutine may run in an executor
        ctx = _ctx(tmp_path, "m.py", """\
            import time

            async def f():
                def worker():
                    time.sleep(1)
                return worker
            """)
        assert async_safety.check(ctx) == []

    def test_a04_threading_lock(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import threading

            lock = threading.Lock()

            async def f():
                with lock:
                    pass
            """)
        assert "A04" in _codes(async_safety.check(ctx))

    def test_a04_asyncio_lock_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            lock = asyncio.Lock()

            async def f():
                async with lock:
                    pass
            """)
        assert async_safety.check(ctx) == []


# -- tracer-purity -----------------------------------------------------------


class TestTracerPurity:
    def test_j01_float_on_traced(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import jax

            @jax.jit
            def f(x):
                return float(x) + 1.0
            """)
        assert "J01" in _codes(tracer_purity.check(ctx))

    def test_j01_item(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import jax

            @jax.jit
            def f(x):
                return x.sum().item()
            """)
        assert "J01" in _codes(tracer_purity.check(ctx))

    def test_j01_static_argname_exempt(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import functools

            import jax

            @functools.partial(jax.jit, static_argnames=("n",))
            def f(x, n):
                return x * int(n)
            """)
        assert tracer_purity.check(ctx) == []

    def test_j02_numpy_in_trace(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return np.cumsum(x)
            """)
        assert "J02" in _codes(tracer_purity.check(ctx))

    def test_j02_dtype_constructor_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import jax
            import jax.numpy as jnp
            import numpy as np

            @jax.jit
            def f(x):
                return jnp.cumsum(x.astype(np.int32))
            """)
        assert tracer_purity.check(ctx) == []

    def test_j03_time_read(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import time

            import jax

            @jax.jit
            def f(x):
                return x + time.monotonic()
            """)
        assert "J03" in _codes(tracer_purity.check(ctx))

    def test_j03_reaches_helpers(self, tmp_path):
        # the call graph extends the root set to module helpers
        ctx = _ctx(tmp_path, "m.py", """\
            import random

            import jax

            def helper(x):
                return x * random.random()

            @jax.jit
            def f(x):
                return helper(x)
            """)
        assert "J03" in _codes(tracer_purity.check(ctx))

    def test_j04_scan_body_mutation(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import jax
            from jax import lax

            seen = []

            def body(carry, x):
                seen.append(x)
                return carry + x, x

            def run(xs):
                return lax.scan(body, 0, xs)
            """)
        assert "J04" in _codes(tracer_purity.check(ctx))

    def test_j04_carry_threading_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import jax
            from jax import lax

            def body(carry, x):
                acc = carry + x
                return acc, acc

            def run(xs):
                return lax.scan(body, 0, xs)
            """)
        assert tracer_purity.check(ctx) == []

    def test_non_jax_module_skipped(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import time

            def f(x):
                return float(x) + time.time()
            """)
        assert tracer_purity.check(ctx) == []


# -- wire-schema -------------------------------------------------------------


class TestWireSchema:
    def test_w01_w02_function_pair(self, tmp_path):
        ctx = _ctx(tmp_path, "codec.py", """\
            def ping_to_wire(m):
                return {"a": m.a, "b": m.b}

            def ping_from_wire(d):
                return (d["a"], d.get("c"))
            """)
        found = wire_schema.check_project(
            [ctx], modules=("codec.py",), envelope_groups=())
        assert _codes(found) == ["W01", "W02"]
        assert "'b'" in found[0].message   # written, never read
        assert "'c'" in found[1].message   # read, never written

    def test_class_pair_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "codec.py", """\
            class Ping:
                def to_wire(self):
                    return {"a": self.a}

                @classmethod
                def from_wire(cls, d):
                    return cls(d.get("a"))
            """)
        assert wire_schema.check_project(
            [ctx], modules=("codec.py",), envelope_groups=()) == []

    def test_one_sided_unit_skipped(self, tmp_path):
        # the peer lives outside the scanned surface — no findings
        ctx = _ctx(tmp_path, "codec.py", """\
            def ping_to_wire(m):
                return {"a": m.a}
            """)
        assert wire_schema.check_project(
            [ctx], modules=("codec.py",), envelope_groups=()) == []

    def test_envelope_group_cross_file(self, tmp_path):
        srv = _ctx(tmp_path, "srv.py", """\
            def reply(w, body):
                w.send({"Seq": 1, "Error": "", "Extra": body})
            """)
        cli = _ctx(tmp_path, "cli.py", """\
            def read(d):
                return d["Seq"], d.get("Error"), d.get("Missing")
            """)
        found = wire_schema.check_project(
            [srv, cli], modules=("srv.py", "cli.py"),
            envelope_groups=(("env", ("srv.py", "cli.py")),))
        assert _codes(found) == ["W02", "W01"]  # sorted by path
        assert "'Missing'" in found[0].message
        assert "'Extra'" in found[1].message

    def test_repo_wire_surface_clean(self):
        roots = [str(REPO / m) for m in wire_schema.WIRE_MODULES]
        result = run_vet(roots, passes=["wire-schema"], baseline_path=None)
        assert result.findings == []


# -- exception-hygiene -------------------------------------------------------


class TestExceptionHygiene:
    def test_e01_bare_except(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            def f():
                try:
                    return 1
                except:
                    return 0
            """)
        assert "E01" in _codes(exceptions.check(ctx))

    def test_e02_silent_broad(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            def f():
                try:
                    return 1
                except Exception:
                    pass
            """)
        assert "E02" in _codes(exceptions.check(ctx))

    def test_e02_handled_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import logging

            def f():
                try:
                    return 1
                except Exception:
                    logging.exception("f failed")
            """)
        assert exceptions.check(ctx) == []

    def test_e03_tuple_with_cancelled(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            async def f(task):
                try:
                    await task
                except (asyncio.CancelledError, ValueError):
                    pass
            """)
        assert "E03" in _codes(exceptions.check(ctx))

    def test_e03_cancel_only_exempt(self, tmp_path):
        # the deliberate cancel-then-await idiom
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            async def f(task):
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
            """)
        assert exceptions.check(ctx) == []

    def test_e03_reraise_exempt(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            async def f(task):
                try:
                    await task
                except BaseException:
                    task = None
                    raise
            """)
        assert _codes(exceptions.check(ctx)) == []

    def test_e03_sync_function_exempt(self, tmp_path):
        # no coroutine, no cancellation to swallow (still E02 though)
        ctx = _ctx(tmp_path, "m.py", """\
            def f():
                try:
                    return 1
                except BaseException:
                    pass
            """)
        assert _codes(exceptions.check(ctx)) == ["E02"]


# -- donation ----------------------------------------------------------------

# indented to match the test-body snippets: _ctx dedents the
# concatenation in one piece
_DONATING_STEP = """\
            import functools

            import jax

            @functools.partial(jax.jit, donate_argnames=("state",))
            def step(state, key):
                return state + key

"""


class TestDonation:
    def test_d01_use_after_donate(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _DONATING_STEP + """\
            def drive(state, key):
                out = step(state, key)
                return state + out
            """)
        found = donation.check_project([ctx])
        assert _codes(found) == ["D01"]
        assert "'state'" in found[0].message

    def test_d01_rebind_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _DONATING_STEP + """\
            def drive(state, key):
                state = step(state, key)
                return state
            """)
        assert donation.check_project([ctx]) == []

    def test_d01_loop_carried(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _DONATING_STEP + """\
            def drive(state, keys):
                for k in keys:
                    step(state, k)
            """)
        found = donation.check_project([ctx])
        assert _codes(found) == ["D01"]
        assert "loop" in found[0].message

    def test_d01_block_until_ready_observe_clean(self, tmp_path):
        # the deliberate observe-deletion idiom (test_shard_map_parity)
        ctx = _ctx(tmp_path, "m.py", _DONATING_STEP + """\
            def drive(state, key):
                out = step(state, key)
                jax.block_until_ready(state)
                return out
            """)
        assert donation.check_project([ctx]) == []

    def test_d01_traced_caller_exempt(self, tmp_path):
        # an inner donating jit inlines under the outer trace — nothing
        # is consumed at trace time (tools/profile_kernel.py relies on
        # this)
        ctx = _ctx(tmp_path, "m.py", _DONATING_STEP + """\
            @jax.jit
            def outer(state, key):
                s1 = step(state, key)
                s2 = step(state, key)
                return s1 + s2
            """)
        assert donation.check_project([ctx]) == []

    def test_d02_donated_attribute(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _DONATING_STEP + """\
            class Plane:
                def tick(self, key):
                    step(self._state, key)
            """)
        found = donation.check_project([ctx])
        assert _codes(found) == ["D02"]
        assert "self._state" in found[0].message

    def test_d02_attribute_rebind_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _DONATING_STEP + """\
            class Plane:
                def tick(self, key):
                    self._state = step(self._state, key)
            """)
        assert donation.check_project([ctx]) == []

    def test_d01_factory_assigned_donor(self, tmp_path):
        # fn = factory(...) where the factory returns a donating jit
        ctx = _ctx(tmp_path, "m.py", """\
            import jax

            def make_step(p):
                def impl(state, key):
                    return state + key + p
                return jax.jit(impl, donate_argnums=(0,))

            step2 = make_step(1)

            def drive(state, key):
                out = step2(state, key)
                return state
            """)
        found = donation.check_project([ctx])
        assert _codes(found) == ["D01"]

    def test_d01_cross_file_donor(self, tmp_path):
        kernel = _ctx(tmp_path, "kern.py", _DONATING_STEP)
        caller = _ctx(tmp_path, "call.py", """\
            import jax

            from kern import step

            def drive(state, key):
                fresh = step(state, key)
                return state, fresh
            """)
        found = donation.check_project([kernel, caller])
        assert _codes(found) == ["D01"]
        assert found[0].path.endswith("call.py")


# -- shard-exact -------------------------------------------------------------

_SHARD_HEADER = """\
            import jax
            import jax.numpy as jnp
            from jax.experimental.shard_map import shard_map

"""


class TestShardExact:
    def test_s01_float_psum(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _SHARD_HEADER + """\
            def body(x):
                return jax.lax.psum(x.astype(jnp.float32), "i")

            def run(mesh, specs, x):
                return shard_map(body, mesh, in_specs=specs,
                                 out_specs=specs)(x)
            """)
        found = shard_exact.check(ctx)
        assert _codes(found) == ["S01"]
        assert "float32" in found[0].message

    def test_s01_int_psum_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _SHARD_HEADER + """\
            def body(x):
                return jax.lax.psum(x.astype(jnp.int32), "i")

            def run(mesh, specs, x):
                return shard_map(body, mesh, in_specs=specs,
                                 out_specs=specs)(x)
            """)
        assert shard_exact.check(ctx) == []

    def test_s01_pmean_always(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _SHARD_HEADER + """\
            def body(x):
                return jax.lax.pmean(x, "i")

            def run(mesh, specs, x):
                return shard_map(body, mesh, in_specs=specs,
                                 out_specs=specs)(x)
            """)
        assert _codes(shard_exact.check(ctx)) == ["S01"]

    def test_s02_ungated_scatter(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _SHARD_HEADER + """\
            def body(x, reg):
                i = jax.lax.axis_index("i")
                return reg.at[i].set(x)

            def run(mesh, specs, x, reg):
                return shard_map(body, mesh, in_specs=specs,
                                 out_specs=specs)(x, reg)
            """)
        found = shard_exact.check(ctx)
        assert _codes(found) == ["S02"]

    def test_s02_owner_gated_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _SHARD_HEADER + """\
            def body(x, reg, owned):
                i = jax.lax.axis_index("i")
                return reg.at[jnp.where(owned, i, 10**9)].set(
                    x, mode="drop")

            def run(mesh, specs, x, reg, owned):
                return shard_map(body, mesh, in_specs=specs,
                                 out_specs=specs)(x, reg, owned)
            """)
        assert shard_exact.check(ctx) == []

    def test_s03_duplicate_destination(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _SHARD_HEADER + """\
            def body(x):
                return jax.lax.ppermute(x, "i", perm=[(0, 1), (1, 1)])

            def run(mesh, specs, x):
                return shard_map(body, mesh, in_specs=specs,
                                 out_specs=specs)(x)
            """)
        found = shard_exact.check(ctx)
        assert _codes(found) == ["S03"]
        assert "destination" in found[0].message

    def test_s03_constant_comprehension_element(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _SHARD_HEADER + """\
            def body(x):
                return jax.lax.ppermute(
                    x, "i", perm=[(i, 0) for i in range(4)])

            def run(mesh, specs, x):
                return shard_map(body, mesh, in_specs=specs,
                                 out_specs=specs)(x)
            """)
        assert _codes(shard_exact.check(ctx)) == ["S03"]

    def test_s03_rotation_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _SHARD_HEADER + """\
            def body(x):
                return jax.lax.ppermute(
                    x, "i", perm=[(i, (i + 1) % 4) for i in range(4)])

            def run(mesh, specs, x):
                return shard_map(body, mesh, in_specs=specs,
                                 out_specs=specs)(x)
            """)
        assert shard_exact.check(ctx) == []


# -- carry-contract ----------------------------------------------------------


class TestCarryContract:
    def test_c01_reordered_legs(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import jax
            from jax import lax

            def body(carry, x):
                a, b = carry
                return (b, a), x

            def run(xs):
                return lax.scan(body, (0, 1), xs)
            """)
        found = carry_contract.check(ctx)
        assert _codes(found) == ["C01"]
        assert "reorders" in found[0].message

    def test_c01_dropped_leg_while_loop(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import jax
            from jax import lax

            def cond(carry):
                a, b = carry
                return a < b

            def body(carry):
                a, b = carry
                return (a,)

            def run():
                return lax.while_loop(cond, body, (0, 10))
            """)
        found = carry_contract.check(ctx)
        assert _codes(found) == ["C01"]
        assert "'b'" in found[0].message

    def test_c02_cast_leg(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import jax
            import jax.numpy as jnp
            from jax import lax

            def body(carry, x):
                a, b = carry
                return (a, b.astype(jnp.int16)), x

            def run(xs):
                return lax.scan(body, (jnp.int32(0), jnp.int32(0)), xs)
            """)
        found = carry_contract.check(ctx)
        assert _codes(found) == ["C02"]
        assert "init pins int32" in found[0].message

    def test_c02_cast_to_pinned_dtype_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import jax
            import jax.numpy as jnp
            from jax import lax

            def body(carry, x):
                a, b = carry
                return (a, b.astype(jnp.int16)), x

            def run(xs):
                return lax.scan(body, (jnp.int32(0), jnp.int16(0)), xs)
            """)
        assert carry_contract.check(ctx) == []

    def test_clean_threading(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import jax
            from jax import lax

            def body(carry, x):
                a, b = carry
                return (a, b), x

            def run(xs):
                return lax.scan(body, (0, 1), xs)
            """)
        assert carry_contract.check(ctx) == []

    def test_constructed_carry_skipped(self, tmp_path):
        # _replace / conditional carries are the tracer's to check
        ctx = _ctx(tmp_path, "m.py", """\
            import jax
            from jax import lax

            def body(carry, x):
                st = carry
                return st._replace(round=st.round + 1), x

            def run(st, xs):
                return lax.scan(body, st, xs)
            """)
        assert carry_contract.check(ctx) == []


# -- overflow ----------------------------------------------------------------

_JAX_HEADER = """\
            import jax
            import jax.numpy as jnp

"""


class TestOverflow:
    def test_o01_carry_accumulator(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _JAX_HEADER + """\
            @jax.jit
            def step(state, xs):
                n_seen = state.n_seen + jnp.sum(xs)
                return n_seen
            """)
        found = overflow.check(ctx)
        assert _codes(found) == ["O01"]
        assert "'n_seen'" in found[0].message

    def test_o01_replace_kwarg(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _JAX_HEADER + """\
            @jax.jit
            def step(state, fresh):
                return state._replace(n=state.n + jnp.sum(fresh))
            """)
        assert _codes(overflow.check(ctx)) == ["O01"]

    def test_o01_scatter_add(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _JAX_HEADER + """\
            @jax.jit
            def step(bank, idx):
                return bank.at[idx].add(1)
            """)
        found = overflow.check(ctx)
        assert _codes(found) == ["O01"]
        assert "scatter-add" in found[0].message

    def test_o01_conditional_accumulate(self, tmp_path):
        # x = where(c, x + inc, x) is still an accumulate
        ctx = _ctx(tmp_path, "m.py", _JAX_HEADER + """\
            @jax.jit
            def step(state, inc):
                total = jnp.where(inc > 0, state.total + inc, state.total)
                return total
            """)
        assert _codes(overflow.check(ctx)) == ["O01"]

    def test_o01_small_constant_clean(self, tmp_path):
        # +1 per round stays under 2**31 for a day at 10k rounds/s
        ctx = _ctx(tmp_path, "m.py", _JAX_HEADER + """\
            @jax.jit
            def step(state):
                return state._replace(round=state.round + 1)
            """)
        assert overflow.check(ctx) == []

    def test_o01_bool_mask_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _JAX_HEADER + """\
            @jax.jit
            def step(state, xs):
                fresh = xs > 0
                n = state.n + fresh.astype(jnp.int32)
                return n
            """)
        assert overflow.check(ctx) == []

    def test_o01_round_local_clean(self, tmp_path):
        # freshly constructed each call: bounded by one round's work
        ctx = _ctx(tmp_path, "m.py", _JAX_HEADER + """\
            @jax.jit
            def step(hits):
                n_sus = jnp.zeros((4,), jnp.int32)
                n_sus = n_sus + hits
                return n_sus
            """)
        assert overflow.check(ctx) == []

    def test_o01_periodic_reset_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _JAX_HEADER + """\
            @jax.jit
            def step(total, inc, flag):
                total = total + inc
                total = jnp.where(flag, 0, total)
                return total
            """)
        assert overflow.check(ctx) == []

    def test_o02_mixed_width(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _JAX_HEADER + """\
            @jax.jit
            def step(a, b):
                return a.astype(jnp.int16) + b.astype(jnp.int32)
            """)
        found = overflow.check(ctx)
        assert _codes(found) == ["O02"]
        assert "int16" in found[0].message

    def test_o02_same_width_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", _JAX_HEADER + """\
            @jax.jit
            def step(a, b):
                return a.astype(jnp.int32) + b.astype(jnp.int32)
            """)
        assert overflow.check(ctx) == []

    def test_untraced_host_code_exempt(self, tmp_path):
        # host-side Python wraps into Python ints — not the kernel's
        # problem
        ctx = _ctx(tmp_path, "m.py", _JAX_HEADER + """\
            def drain(state, xs):
                return state.n_seen + jnp.sum(xs)
            """)
        assert overflow.check(ctx) == []


# -- suppression: noqa + baseline --------------------------------------------


class TestSuppression:
    def test_parse_noqa_forms(self):
        noqa = parse_noqa("x = 1  # noqa\ny = 2  # noqa: A02, e03\nz = 3\n")
        assert noqa[1] is None            # blanket
        assert noqa[2] == {"A02", "E03"}  # codes, case-folded
        assert 3 not in noqa

    def test_noqa_code_suppresses(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text(textwrap.dedent("""\
            import asyncio

            async def main():
                asyncio.create_task(asyncio.sleep(1))  # noqa: A02
            """))
        result = run_vet([str(p)], baseline_path=None)
        assert result.findings == []

    def test_noqa_wrong_code_does_not_suppress(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text(textwrap.dedent("""\
            import asyncio

            async def main():
                asyncio.create_task(asyncio.sleep(1))  # noqa: E02
            """))
        result = run_vet([str(p)], baseline_path=None)
        assert _codes(result.findings) == ["A02"]

    def test_blanket_noqa_suppresses_everything(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text(textwrap.dedent("""\
            import asyncio

            async def main():
                asyncio.create_task(asyncio.sleep(1))  # noqa
            """))
        result = run_vet([str(p)], baseline_path=None)
        assert result.findings == []

    def test_baseline_suppresses_and_counts(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("def f():\n    try:\n        return 1\n"
                     "    except Exception:\n        pass\n")
        unsuppressed = run_vet([str(p)], baseline_path=None)
        assert _codes(unsuppressed.findings) == ["E02"]
        base = tmp_path / "baseline.txt"
        base.write_text("# justified: fixture\n"
                        + unsuppressed.findings[0].baseline_key() + "\n")
        result = run_vet([str(p)], baseline_path=base)
        assert result.findings == []
        assert result.baselined == 1
        assert result.rc == 0

    def test_stale_baseline_entry_reported(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("x = 1\n")
        base = tmp_path / "baseline.txt"
        base.write_text("gone.py|E02|no longer found\n")
        result = run_vet([str(p)], baseline_path=base)
        assert result.stale_baseline == ["gone.py|E02|no longer found"]

    def test_multi_code_noqa_suppresses_both(self, tmp_path):
        # one line, two codes from the overflow pass: O01 (accumulator)
        # and O02 (mixed width inside the increment)
        src = textwrap.dedent("""\
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(state, x, y):
                n = state.n + jnp.sum(x.astype(jnp.int16) + y.astype(jnp.int32)){noqa}
                return n
            """)
        p = tmp_path / "m.py"
        p.write_text(src.format(noqa=""))
        both = run_vet([str(p)], baseline_path=None)
        assert sorted(_codes(both.findings)) == ["O01", "O02"]
        p.write_text(src.format(noqa="  # noqa: O01,O02"))
        assert run_vet([str(p)], baseline_path=None).findings == []

    def test_multi_code_noqa_is_not_blanket(self, tmp_path):
        src = textwrap.dedent("""\
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(state, x, y):
                n = state.n + jnp.sum(x.astype(jnp.int16) + y.astype(jnp.int32))  # noqa: O01
                return n
            """)
        p = tmp_path / "m.py"
        p.write_text(src)
        result = run_vet([str(p)], baseline_path=None)
        assert _codes(result.findings) == ["O02"]  # only O01 suppressed

    def test_stale_baseline_across_new_pass_codes(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("x = 1\n")
        base = tmp_path / "baseline.txt"
        base.write_text("gone.py|D01|old donation finding\n"
                        "gone.py|S02|old scatter finding\n"
                        "gone.py|O01|old overflow finding\n"
                        "gone.py|X01|old interleave finding\n"
                        "gone.py|T02|old lease-leak finding\n")
        result = run_vet([str(p)], baseline_path=base)
        assert sorted(k.split("|")[1] for k in result.stale_baseline) \
            == ["D01", "O01", "S02", "T02", "X01"]
        assert result.rc == 0  # stale entries warn, they don't fail

    def test_write_baseline_roundtrip(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("def f():\n    try:\n        return 1\n"
                     "    except Exception:\n        pass\n")
        base = tmp_path / "baseline.txt"
        first = run_vet([str(p)], baseline_path=base, update_baseline=True)
        assert first.findings == [] and first.baselined == 1
        again = run_vet([str(p)], baseline_path=base)
        assert again.rc == 0 and again.stale_baseline == []


# -- exit codes (the `make vet` contract) ------------------------------------


class TestExitCodes:
    def test_rc0_clean(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("x = 1\n")
        assert vet_main([str(p), "--no-baseline"]) == 0

    def test_rc1_findings(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("def f():\n    try:\n        return 1\n"
                     "    except:\n        pass\n")
        assert vet_main([str(p), "--no-baseline"]) == 1

    def test_rc2_parse_error(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("def f(:\n")
        assert vet_main([str(p), "--no-baseline"]) == 2

    def test_rc2_unknown_pass(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("x = 1\n")
        assert vet_main([str(p), "--passes", "nope"]) == 2

    def test_pass_subset_runs_only_that_pass(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("import os\n\n\ndef f():\n    try:\n        return 1\n"
                     "    except:\n        pass\n")
        result = run_vet([str(p)], passes=["names"], baseline_path=None)
        assert _codes(result.findings) == ["N02"]  # E01 pass not selected

    def test_legacy_pyvet_cli_still_names_only(self, tmp_path):
        from tools import pyvet
        p = tmp_path / "m.py"
        p.write_text("def f():\n    try:\n        return 1\n"
                     "    except:\n        pass\n")
        with pytest.warns(DeprecationWarning, match="deprecated"):
            assert pyvet.main([str(p)]) == 0  # E01 is not a legacy pass


# -- output formats (the CI artifact surface) --------------------------------

_OVERFLOW_DEFECT = """\
import jax
import jax.numpy as jnp

@jax.jit
def step(state, xs):
    return state._replace(n=state.n + jnp.sum(xs))
"""


class TestOutputFormats:
    def test_format_json(self, tmp_path, capsys):
        p = tmp_path / "m.py"
        p.write_text(_OVERFLOW_DEFECT)
        rc = vet_main([str(p), "--no-baseline", "--format", "json"])
        data = json.loads(capsys.readouterr().out)
        assert rc == 1 and data["rc"] == 1
        assert data["files"] == 1
        assert [f["code"] for f in data["findings"]] == ["O01"]
        assert data["findings"][0]["path"].endswith("m.py")
        assert data["per_pass"]["overflow"] == 1

    def test_report_artifact_written(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("x = 1\n")
        report = tmp_path / "vet_report.json"
        rc = vet_main([str(p), "--no-baseline", "--report", str(report)])
        data = json.loads(report.read_text())
        assert rc == 0 and data["rc"] == 0
        assert data["findings"] == [] and data["files"] == 1

    def test_fast_skips_flow_passes(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text(_OVERFLOW_DEFECT)
        assert vet_main([str(p), "--no-baseline"]) == 1
        assert vet_main([str(p), "--no-baseline", "--fast"]) == 0


# -- pallas-safety (P01-P04) -------------------------------------------------


_PALLAS_HEAD = (
    "import jax\n"
    "from jax.experimental import pallas as pl\n"
    "from jax.experimental.pallas import tpu as pltpu\n")


def _pallas_ctx(tmp_path, src):
    return _ctx(tmp_path, "m.py", _PALLAS_HEAD + textwrap.dedent(src))


class TestPallasSafety:
    def test_unguarded_divisibility_fires(self, tmp_path):
        # the acceptance-criteria defect: a runtime-unguarded
        # `N // nb` block width feeding a pallas_call BlockSpec
        ctx = _pallas_ctx(tmp_path, """\
            def f(x, nb):
                N = x.shape[1]
                Bn = N // nb
                def kern(x_ref, o_ref):
                    o_ref[...] = x_ref[...]
                return pl.pallas_call(
                    kern, grid=(nb,),
                    in_specs=[pl.BlockSpec((8, Bn), lambda j: (0, j))],
                    out_specs=pl.BlockSpec((8, Bn), lambda j: (0, j)),
                    out_shape=x, interpret=True)(x)
            """)
        assert "P01" in _codes(pallas_safety.check(ctx))

    def test_statically_violated_divisibility_fires(self, tmp_path):
        # constant-folded with the SAME divides() the runtime uses
        ctx = _pallas_ctx(tmp_path, """\
            def f(x):
                Bn = 10 // 3
                def kern(x_ref, o_ref):
                    o_ref[...] = x_ref[...]
                return pl.pallas_call(
                    kern, grid=(3,),
                    in_specs=[pl.BlockSpec((8, Bn), lambda j: (0, j))],
                    out_shape=x, interpret=True)(x)
            """)
        found = [f for f in pallas_safety.check(ctx) if f.code == "P01"]
        assert found and "does not tile" in found[0].message

    def test_shared_helper_guard_is_clean(self, tmp_path):
        ctx = _pallas_ctx(tmp_path, """\
            from consul_tpu.ops.divisibility import require_divisible
            def f(x, nb):
                N = x.shape[1]
                require_divisible(N, nb, what="n", by="nb")
                Bn = N // nb
                def kern(x_ref, o_ref):
                    o_ref[...] = x_ref[...]
                return pl.pallas_call(
                    kern, grid=(nb,),
                    in_specs=[pl.BlockSpec((8, Bn), lambda j: (0, j))],
                    out_shape=x, interpret=True)(x)
            """)
        assert pallas_safety.check(ctx) == []

    def test_missing_interpret_fires(self, tmp_path):
        ctx = _pallas_ctx(tmp_path, """\
            def f(x):
                def kern(x_ref, o_ref):
                    o_ref[...] = x_ref[...]
                return pl.pallas_call(kern, out_shape=x)(x)
            """)
        assert _codes(pallas_safety.check(ctx)) == ["P02"]

    def test_index_map_without_modulo_fires(self, tmp_path):
        ctx = _pallas_ctx(tmp_path, """\
            def f(x, qr, nb):
                def kern(qr_ref, x_ref, o_ref):
                    o_ref[...] = x_ref[...]
                return pl.pallas_call(
                    kern,
                    grid_spec=pltpu.PrefetchScalarGridSpec(
                        num_scalar_prefetch=1, grid=(nb,),
                        in_specs=[pl.BlockSpec(
                            (8, 8), lambda j, qr: (0, j - qr[0]))],
                        out_specs=pl.BlockSpec(
                            (8, 8), lambda j, qr: (0, j)),
                    ),
                    out_shape=x, interpret=True)(qr, x)
            """)
        assert "P03" in _codes(pallas_safety.check(ctx))

    def test_dynamic_slice_without_certificate_fires(self, tmp_path):
        ctx = _pallas_ctx(tmp_path, """\
            def f(x, offs, nb):
                def kern(qr_ref, x_ref, o_ref):
                    r = qr_ref[0]
                    o_ref[...] = jax.lax.dynamic_slice(
                        x_ref[...], (0, r), (8, 8))
                return pl.pallas_call(
                    kern,
                    grid_spec=pltpu.PrefetchScalarGridSpec(
                        num_scalar_prefetch=1, grid=(nb,),
                        in_specs=[pl.BlockSpec(
                            (8, 8), lambda j, qr: (0, j))],
                        out_specs=pl.BlockSpec(
                            (8, 8), lambda j, qr: (0, j)),
                    ),
                    out_shape=x, interpret=True)(offs, x)
            """)
        assert "P03" in _codes(pallas_safety.check(ctx))

    def test_residue_certificate_is_clean(self, tmp_path):
        # the gossip/fused.py shape: the prefetch operand is built
        # with `offs % Bn`, bounding the in-kernel splice
        ctx = _pallas_ctx(tmp_path, """\
            def f(x, offs, nb, Bn):
                def kern(qr_ref, x_ref, o_ref):
                    r = qr_ref[0]
                    o_ref[...] = jax.lax.dynamic_slice(
                        x_ref[...], (0, r), (8, 8))
                qr = (offs % Bn).astype(int)
                return pl.pallas_call(
                    kern,
                    grid_spec=pltpu.PrefetchScalarGridSpec(
                        num_scalar_prefetch=1, grid=(nb,),
                        in_specs=[pl.BlockSpec(
                            (8, 8), lambda j, qr: (0, j))],
                        out_specs=pl.BlockSpec(
                            (8, 8), lambda j, qr: (0, j)),
                    ),
                    out_shape=x, interpret=True)(qr, x)
            """)
        assert pallas_safety.check(ctx) == []

    def test_prefetch_indexed_by_program_id_fires(self, tmp_path):
        ctx = _pallas_ctx(tmp_path, """\
            def f(x, qr, nb):
                def kern(qr_ref, x_ref, o_ref):
                    v = qr_ref[pl.program_id(0)]
                    o_ref[...] = x_ref[...] + v
                return pl.pallas_call(
                    kern,
                    grid_spec=pltpu.PrefetchScalarGridSpec(
                        num_scalar_prefetch=1, grid=(nb,),
                        in_specs=[pl.BlockSpec(
                            (8, 8), lambda j, qr: (0, j))],
                        out_specs=pl.BlockSpec(
                            (8, 8), lambda j, qr: (0, j)),
                    ),
                    out_shape=x, interpret=True)(qr, x)
            """)
        assert "P04" in _codes(pallas_safety.check(ctx))

    def test_static_prefetch_reads_are_clean(self, tmp_path):
        # Python-int indexing of the scalar ref (the fused.py idiom:
        # qr_ref[fanout + f] with both names loop-static)
        ctx = _pallas_ctx(tmp_path, """\
            def f(x, qr, nb, fanout):
                def kern(qr_ref, x_ref, o_ref):
                    for g in range(fanout):
                        v = qr_ref[fanout + g]
                    o_ref[...] = x_ref[...]
                return pl.pallas_call(
                    kern,
                    grid_spec=pltpu.PrefetchScalarGridSpec(
                        num_scalar_prefetch=1, grid=(nb,),
                        in_specs=[pl.BlockSpec(
                            (8, 8), lambda j, qr: (0, j))],
                        out_specs=pl.BlockSpec(
                            (8, 8), lambda j, qr: (0, j)),
                    ),
                    out_shape=x, interpret=True)(qr, x)
            """)
        assert pallas_safety.check(ctx) == []

    def test_real_fused_kernel_is_clean(self):
        ctx = FileCtx.load(REPO / "consul_tpu/gossip/fused.py",
                           "consul_tpu/gossip/fused.py")
        assert pallas_safety.check(ctx) == []


class TestDivisibilityHelper:
    """The satellite contract: runtime guard and static pass consume
    the SAME helper, so they cannot disagree."""

    def test_require_divisible_agrees_with_divides(self):
        from consul_tpu.ops.divisibility import divides, require_divisible
        for n in range(0, 40):
            for d in range(0, 8):
                if divides(n, d):
                    require_divisible(n, d)
                else:
                    with pytest.raises(ValueError):
                        require_divisible(n, d)

    def test_kernel_and_pass_share_the_helper(self):
        fused_src = (REPO / "consul_tpu/gossip/fused.py").read_text()
        assert ("from consul_tpu.ops.divisibility import "
                "require_divisible") in fused_src
        assert "require_divisible(N, nb" in fused_src
        pass_src = (REPO / "tools/vet/pallas_safety.py").read_text()
        assert ("from consul_tpu.ops.divisibility import divides"
                in pass_src)


# -- table-drift (K01-K02) ---------------------------------------------------


_GOVERNING_DISSEM = """\
    class SwimParams:
        def __post_init__(self):
            if self.dissem not in ("swar", "planes", "prefused", "fused"):
                raise ValueError("dissem")
    """


class TestTableDrift:
    def _ctxs(self, tmp_path, devstats_body):
        return [
            _ctx(tmp_path, "consul_tpu/gossip/params.py",
                 _GOVERNING_DISSEM),
            _ctx(tmp_path, "consul_tpu/obs/devstats.py", devstats_body),
        ]

    def test_synced_table_is_clean(self, tmp_path):
        ctxs = self._ctxs(tmp_path, """\
            DENSE_PASSES_BY_DISSEM = {"swar": 5, "planes": 5,
                                      "prefused": 4, "fused": 2}
            """)
        assert table_drift.check_project(ctxs) == []

    def test_desynced_table_fires(self, tmp_path):
        ctxs = self._ctxs(tmp_path, """\
            DENSE_PASSES_BY_DISSEM = {"swar": 5, "planes": 5,
                                      "fused": 2, "xla": 9}
            """)
        found = [f for f in table_drift.check_project(ctxs)
                 if f.code == "K01"]
        assert found
        assert "prefused" in found[0].message  # missing
        assert "xla" in found[0].message       # extra

    def test_renamed_table_fires(self, tmp_path):
        # a silently-renamed table is drift, not absence
        ctxs = self._ctxs(tmp_path, """\
            PASSES_BY_STRATEGY = {"swar": 5}
            """)
        found = [f for f in table_drift.check_project(ctxs)
                 if f.code == "K01"]
        assert found and "not found" in found[0].message

    def test_stray_dispatch_literal_fires(self, tmp_path):
        ctxs = self._ctxs(tmp_path, """\
            DENSE_PASSES_BY_DISSEM = {"swar": 5, "planes": 5,
                                      "prefused": 4, "fused": 2}
            """) + [_ctx(tmp_path, "caller.py", """\
            def bench(params_cls):
                return params_cls(n=64, dissem="florp")
            """)]
        found = [f for f in table_drift.check_project(ctxs)
                 if f.code == "K02"]
        assert found and "florp" in found[0].message

    def test_valid_dispatch_literal_is_clean(self, tmp_path):
        ctxs = self._ctxs(tmp_path, """\
            DENSE_PASSES_BY_DISSEM = {"swar": 5, "planes": 5,
                                      "prefused": 4, "fused": 2}
            """) + [_ctx(tmp_path, "caller.py", """\
            def bench(params_cls):
                if params_cls.dissem == "fused":
                    return params_cls(n=64, dissem="swar")
            """)]
        assert table_drift.check_project(ctxs) == []

    def test_governing_file_absent_skips_group(self, tmp_path):
        # subset runs (unit fixtures, --changed) must not false-fire
        ctxs = [_ctx(tmp_path, "consul_tpu/obs/devstats.py", """\
            DENSE_PASSES_BY_DISSEM = {"swar": 5}
            """)]
        assert table_drift.check_project(ctxs) == []

    def test_gauge_help_mention_drift_fires(self, tmp_path):
        ctxs = [
            _ctx(tmp_path, "consul_tpu/state/device_store.py", """\
                def pick(match_backend):
                    if match_backend not in ("auto", "device", "host"):
                        raise ValueError(match_backend)
                """),
            _ctx(tmp_path, "consul_tpu/obs/storestats.py", """\
                def gauges(self):
                    return [{
                        "name": "consul_watch_match_backend",
                        "help": "1 = device matcher selected.",
                        "rows": [],
                    }]
                """),
        ]
        found = [f for f in table_drift.check_project(ctxs)
                 if f.code == "K01"]
        assert found and "host" in found[0].message

    def test_desynced_copy_of_real_sources_fires(self, tmp_path):
        """The K01 meta-test: copies of the REAL params.py + devstats.py
        with DENSE_PASSES_BY_DISSEM deliberately desynced must fire —
        pins that the extractors still parse the production idiom."""
        params_src = (REPO / "consul_tpu/gossip/params.py").read_text()
        dev_src = (REPO / "consul_tpu/obs/devstats.py").read_text()
        assert '"prefused": 4, ' in dev_src
        desynced = dev_src.replace('"prefused": 4, ', '', 1)
        ctxs = [
            _ctx(tmp_path, "consul_tpu/gossip/params.py", params_src),
            _ctx(tmp_path, "consul_tpu/obs/devstats.py", desynced),
        ]
        found = [f for f in table_drift.check_project(ctxs)
                 if f.code == "K01"]
        assert found and "prefused" in found[0].message
        # and the unmodified copies are in sync (the live contract)
        ctxs = [
            _ctx(tmp_path, "sync/consul_tpu/gossip/params.py",
                 params_src),
            _ctx(tmp_path, "sync/consul_tpu/obs/devstats.py", dev_src),
        ]
        assert [f for f in table_drift.check_project(ctxs)
                if f.code == "K01"] == []

    # -- union groups (the autotune-knob registry) ---------------------------

    _KNOBS_GOV = """\
        KNOBS = {"dissem": 1, "hot_slots": 2, "http_workers": 3,
                 "watch_device_min": 4}
        """

    # device_store.py is also the match-backend group's governing file,
    # so its fixture must carry that membership idiom or the group
    # fires "governing not found" at the fixture copy.
    _STORE_PREAMBLE = (
        'def pick(match_backend):\n'
        '    if match_backend not in ("auto", "device", "host"):\n'
        '        raise ValueError(match_backend)\n')

    def _union_ctxs(self, tmp_path, plane=None, agent=None, store=None):
        ctxs = [_ctx(tmp_path, "consul_tpu/obs/tuner.py",
                     self._KNOBS_GOV)]
        for relpath, fields in (
                ("consul_tpu/gossip/plane.py", plane),
                ("consul_tpu/agent/agent.py", agent),
                ("consul_tpu/state/device_store.py", store)):
            if fields is not None:
                body = f"TUNED_FIELDS = {fields!r}\n"
                if relpath.endswith("device_store.py"):
                    body = self._STORE_PREAMBLE + body
                ctxs.append(_ctx(tmp_path, relpath, body))
        return ctxs

    def test_union_group_synced_is_clean(self, tmp_path):
        ctxs = self._union_ctxs(
            tmp_path, plane=("dissem", "hot_slots"),
            agent=("http_workers",), store=("watch_device_min",))
        assert table_drift.check_project(ctxs) == []

    def test_union_satellite_extra_key_fires(self, tmp_path):
        # a consumer claiming a knob the registry doesn't define
        ctxs = self._union_ctxs(
            tmp_path, plane=("dissem", "hot_slots", "florp"),
            agent=("http_workers",), store=("watch_device_min",))
        found = [f for f in table_drift.check_project(ctxs)
                 if f.code == "K01"]
        assert found and "florp" in found[0].message

    def test_union_unclaimed_knob_fires(self, tmp_path):
        # a registry knob no consumer resolves — dead tuning surface
        ctxs = self._union_ctxs(
            tmp_path, plane=("dissem", "hot_slots"),
            agent=("http_workers",), store=("hot_slots",))
        found = [f for f in table_drift.check_project(ctxs)
                 if f.code == "K01"]
        assert found and "watch_device_min" in found[0].message

    def test_union_subset_run_skips_completeness(self, tmp_path):
        # with a satellite file absent (unit fixtures, --changed) the
        # union-coverage check must not false-fire; subset claims are
        # still validated
        ctxs = self._union_ctxs(tmp_path, plane=("dissem", "hot_slots"))
        assert table_drift.check_project(ctxs) == []

    def test_union_group_skips_stray_literals(self, tmp_path):
        # K02 is about dispatched keywords; knob names are registry
        # keys, so a stray knob="..." kwarg is not the same contract
        ctxs = self._union_ctxs(
            tmp_path, plane=("dissem", "hot_slots"),
            agent=("http_workers",), store=("watch_device_min",))
        ctxs.append(_ctx(tmp_path, "caller.py", """\
            def f(g):
                return g(knob="florp")
            """))
        assert table_drift.check_project(ctxs) == []

    def test_union_desynced_copy_of_real_sources_fires(self, tmp_path):
        """Union K01 meta-test over copies of the REAL tuner registry
        and consumer TUNED_FIELDS tuples — pins that the extractors
        still parse the production idiom."""
        srcs = {p: (REPO / p).read_text() for p in (
            "consul_tpu/obs/tuner.py",
            "consul_tpu/gossip/plane.py",
            "consul_tpu/agent/agent.py",
            "consul_tpu/state/device_store.py")}
        plane_src = srcs["consul_tpu/gossip/plane.py"]
        assert 'TUNED_FIELDS = ("dissem", ' in plane_src
        desynced = dict(srcs)
        desynced["consul_tpu/gossip/plane.py"] = plane_src.replace(
            'TUNED_FIELDS = ("dissem", ', 'TUNED_FIELDS = (', 1)
        ctxs = [_ctx(tmp_path, p, src) for p, src in desynced.items()]
        found = [f for f in table_drift.check_project(ctxs)
                 if f.code == "K01"]
        assert found and "dissem" in found[0].message
        # and the unmodified copies are in sync (the live contract)
        ctxs = [_ctx(tmp_path, "sync/" + p, src)
                for p, src in srcs.items()]
        assert [f for f in table_drift.check_project(ctxs)
                if f.code == "K01"] == []


# -- fork-safety (R01-R02) ---------------------------------------------------


class TestForkSafety:
    def test_thread_started_before_fork_fires(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import os, threading
            def serve(work):
                t = threading.Thread(target=work, daemon=True)
                t.start()
                return os.fork()
            """)
        assert _codes(fork_safety.check(ctx)) == ["R01"]

    def test_module_level_thread_in_forking_module_fires(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import os, threading
            def work():
                pass
            threading.Thread(target=work, daemon=True).start()
            def serve():
                return os.fork()
            """)
        assert _codes(fork_safety.check(ctx)) == ["R01"]

    def test_fork_then_thread_is_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import os, threading
            def serve(work):
                pid = os.fork()
                if pid == 0:
                    t = threading.Thread(target=work, daemon=True)
                    t.start()
            """)
        assert fork_safety.check(ctx) == []

    def test_popen_is_exempt(self, tmp_path):
        # the agent/workers.py shape: spawn-by-exec, not fork
        ctx = _ctx(tmp_path, "m.py", """\
            import subprocess, threading
            def serve(work):
                t = threading.Thread(target=work, daemon=True)
                t.start()
                return subprocess.Popen(["worker"])
            """)
        assert fork_safety.check(ctx) == []

    def test_unlocked_cross_context_write_fires(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio, threading
            REGISTRY = {}
            def worker():
                REGISTRY["k"] = 1
            async def handler():
                REGISTRY.update(k=2)
            threading.Thread(target=worker).start()
            """)
        assert _codes(fork_safety.check(ctx)) == ["R02", "R02"]

    def test_locked_cross_context_write_is_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio, threading
            REGISTRY = {}
            _LOCK = threading.Lock()
            def worker():
                with _LOCK:
                    REGISTRY["k"] = 1
            async def handler():
                with _LOCK:
                    REGISTRY["k"] = 2
            threading.Thread(target=worker).start()
            """)
        assert fork_safety.check(ctx) == []

    def test_single_context_write_is_clean(self, tmp_path):
        # the repo norm: module state written only from the event loop
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio
            REGISTRY = {}
            async def handler():
                REGISTRY["k"] = 2
            """)
        assert fork_safety.check(ctx) == []


# -- driver: --changed, per-pass timings, stale listing ----------------------


# -- interleave (X01-X04) ----------------------------------------------------


class TestInterleave:
    def test_x01_branch_rmw_across_await(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            class Plane:
                def __init__(self):
                    self.pending = {}
                    self.net = None

                def peek(self):
                    return self.pending

                async def flush(self, key):
                    if key in self.pending:
                        await self.net.send(key)
                        self.pending.pop(key)
            """)
        found = interleave.check(ctx)
        assert _codes(found) == ["X01"]
        assert "every other coroutine may run" in found[0].message

    def test_x01_clean_when_revalidated_after_await(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            class Plane:
                def __init__(self):
                    self.pending = {}
                    self.net = None

                def peek(self):
                    return self.pending

                async def flush(self, key):
                    if key in self.pending:
                        await self.net.send(key)
                        if key in self.pending:
                            self.pending.pop(key)
            """)
        assert interleave.check(ctx) == []

    def test_x01_rmw_expression_with_inline_await(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            class Counter:
                def __init__(self):
                    self.count = 0
                    self.net = None

                def snapshot(self):
                    return self.count

                async def bump(self):
                    self.count = self.count + await self.net.fetch()
            """)
        assert "X01" in _codes(interleave.check(ctx))

    def test_x01_clean_when_await_precedes_read(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            class Counter:
                def __init__(self):
                    self.count = 0
                    self.net = None

                def snapshot(self):
                    return self.count

                async def bump(self):
                    delta = await self.net.fetch()
                    self.count = self.count + delta
            """)
        assert interleave.check(ctx) == []

    def test_x01_swap_then_act_teardown_is_clean(self, tmp_path):
        # The sanctioned teardown idiom: claim the reference
        # synchronously, then await on the local — nothing shared is
        # read after the suspension point.
        ctx = _ctx(tmp_path, "m.py", """\
            class Agent:
                def __init__(self):
                    self.pool = None

                def ready(self):
                    return self.pool is not None

                async def stop(self):
                    pool, self.pool = self.pool, None
                    if pool is not None:
                        await pool.stop()
            """)
        assert interleave.check(ctx) == []

    def test_x02_unguarded_write_to_lock_dominated_field(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class Store:
                def __init__(self):
                    self._lock = asyncio.Lock()
                    self.items = {}

                async def put(self, k, v):
                    async with self._lock:
                        self.items[k] = v

                async def drop(self, k):
                    async with self._lock:
                        self.items.pop(k, None)

                async def get(self, k):
                    async with self._lock:
                        return self.items.get(k)

                async def reset(self):
                    self.items = {}
            """)
        found = interleave.check(ctx)
        assert _codes(found) == ["X02"]
        assert "_lock" in found[0].message

    def test_x02_clean_when_every_write_guarded(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class Store:
                def __init__(self):
                    self._lock = asyncio.Lock()
                    self.items = {}

                async def put(self, k, v):
                    async with self._lock:
                        self.items[k] = v

                async def drop(self, k):
                    async with self._lock:
                        self.items.pop(k, None)

                async def get(self, k):
                    async with self._lock:
                        return self.items.get(k)

                async def reset(self):
                    async with self._lock:
                        self.items = {}
            """)
        assert interleave.check(ctx) == []

    def test_x03_reentrant_acquire_via_self_call(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class S:
                def __init__(self):
                    self._lock = asyncio.Lock()

                async def outer(self):
                    async with self._lock:
                        await self.inner()

                async def inner(self):
                    async with self._lock:
                        pass
            """)
        found = interleave.check(ctx)
        assert _codes(found) == ["X03"]

    def test_x03_clean_when_callee_does_not_lock(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class S:
                def __init__(self):
                    self._lock = asyncio.Lock()

                async def outer(self):
                    async with self._lock:
                        await self._unlocked()

                async def _unlocked(self):
                    pass
            """)
        assert interleave.check(ctx) == []

    def test_x04_thread_and_coroutine_write_unlocked(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import threading

            class M:
                def __init__(self):
                    self.buf = []
                    self._t = threading.Thread(target=self._pump)

                def _pump(self):
                    self.buf.append(1)

                async def drain(self):
                    self.buf = []
            """)
        assert "X04" in _codes(interleave.check(ctx))

    def test_x04_clean_when_coroutine_only_reads(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import threading

            class M:
                def __init__(self):
                    self.buf = []
                    self._t = threading.Thread(target=self._pump)

                def _pump(self):
                    self.buf.append(1)

                async def drain(self):
                    return len(self.buf)
            """)
        assert interleave.check(ctx) == []

    def test_x01_noqa_suppresses(self, tmp_path):
        src = textwrap.dedent("""\
            class Plane:
                def __init__(self):
                    self.pending = {}
                    self.net = None

                def peek(self):
                    return self.pending

                async def flush(self, key):
                    if key in self.pending:
                        await self.net.send(key)
                        self.pending.pop(key)  # noqa: X01
            """)
        p = tmp_path / "m.py"
        p.write_text(src)
        result = run_vet([str(p)], baseline_path=None)
        assert "X01" not in _codes(result.findings)


# -- role-transition (T01-T02) -----------------------------------------------


class TestRoleTransition:
    def test_t01_out_of_band_term_write(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            class Raft:
                def __init__(self):
                    self.role = "Follower"
                    self.current_term = 0
                    self._lease_ack = {}

                def _become_follower(self, term):
                    self.role = "Follower"
                    self.current_term = term
                    self._lease_ack = {}

                async def handle_vote(self, term):
                    self.current_term = term
            """)
        found = role_transition.check(ctx)
        assert _codes(found) == ["T01"]
        assert "handle_vote" in found[0].message

    def test_t01_clean_when_routed_through_helper(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            class Raft:
                def __init__(self):
                    self.role = "Follower"
                    self.current_term = 0
                    self._lease_ack = {}

                def _become_follower(self, term):
                    self.role = "Follower"
                    self.current_term = term
                    self._lease_ack = {}

                async def handle_vote(self, term):
                    self._become_follower(term)
            """)
        assert role_transition.check(ctx) == []

    def test_t02_helper_keeps_stale_lease(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            class Raft:
                def __init__(self):
                    self.role = "Follower"
                    self.current_term = 0
                    self._lease_ack = {}

                def _become_leader(self):
                    self.role = "Leader"
            """)
        found = role_transition.check(ctx)
        assert _codes(found) == ["T02"]
        assert "_lease_ack" in found[0].message

    def test_t02_clean_when_helper_resets_lease(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            class Raft:
                def __init__(self):
                    self.role = "Follower"
                    self.current_term = 0
                    self._lease_ack = {}

                def _become_leader(self):
                    self.role = "Leader"
                    self._lease_ack = {}
            """)
        assert role_transition.check(ctx) == []

    def test_classes_without_become_helpers_exempt(self, tmp_path):
        # role/current_term are common words; only consensus-shaped
        # classes (ones defining _become_*) are held to the discipline.
        ctx = _ctx(tmp_path, "m.py", """\
            class Actor:
                def __init__(self):
                    self.role = "extra"

                def promote(self):
                    self.role = "lead"
            """)
        assert role_transition.check(ctx) == []

    def test_real_raft_is_role_transition_clean(self):
        p = REPO / "consul_tpu" / "consensus" / "raft.py"
        ctx = FileCtx.load(p, "consul_tpu/consensus/raft.py")
        assert role_transition.check(ctx) == []


# -- cancellation safety (Q01-Q04) -------------------------------------------


class TestCancelShield:
    """Q01: bare await of a shared future propagates cancellation."""

    def test_bare_await_of_shared_attr_future(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class Batcher:
                def __init__(self):
                    self._fut = None

                def arm(self):
                    self._fut = asyncio.get_event_loop().create_future()

                def fire(self, val):
                    self._fut.set_result(val)

                async def join(self):
                    return await self._fut
            """)
        found = cancel_safety.check_q01(ctx)
        assert _codes(found) == ["Q01"]
        assert "poisons" in found[0].message

    def test_shielded_await_is_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class Batcher:
                def __init__(self):
                    self._fut = None

                def arm(self):
                    self._fut = asyncio.get_event_loop().create_future()

                def fire(self, val):
                    self._fut.set_result(val)

                async def join(self):
                    return await asyncio.shield(self._fut)
            """)
        assert cancel_safety.check_q01(ctx) == []

    def test_bare_await_of_batch_record_future(self, tmp_path):
        # the confirm-batch shape: a dict-of-dicts whose records carry
        # the shared future under a "fut" key, fetched into a local
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class Srv:
                def __init__(self):
                    self._batches = {}

                async def confirm(self, key):
                    b = self._batches.get(key)
                    if b is None:
                        b = self._batches[key] = {
                            "fut": asyncio.get_event_loop()
                            .create_future()}
                    return await b["fut"]
            """)
        assert _codes(cancel_safety.check_q01(ctx)) == ["Q01"]

    def test_teardown_join_after_own_cancel_is_clean(self, tmp_path):
        # swap-then-cancel stop() idiom: the function reaps a task it
        # itself cancelled — awaiting it bare IS the supervision
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class W:
                def start(self):
                    self._task = asyncio.ensure_future(self._run())

                async def _run(self):
                    await asyncio.sleep(1)

                async def stop(self):
                    t, self._task = self._task, None
                    t.cancel()
                    await t
            """)
        assert cancel_safety.check_q01(ctx) == []


class TestFutureResolution:
    """Q02: a created future must be resolved on every path."""

    def test_local_future_never_resolved_never_escapes(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            def f():
                fut = asyncio.get_event_loop().create_future()
                return 1
            """)
        found = cancel_safety.check_q02(ctx)
        assert _codes(found) == ["Q02"]
        assert "never escapes" in found[0].message

    def test_escaping_future_is_clean(self, tmp_path):
        # returning the future hands resolution responsibility away
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            def f():
                fut = asyncio.get_event_loop().create_future()
                return fut
            """)
        assert cancel_safety.check_q02(ctx) == []

    def test_await_escape_skips_resolution(self, tmp_path):
        # a CancelledError out of _fetch() strands fut's waiters
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class Pump:
                async def run(self, fut):
                    val = await self._fetch()
                    fut.set_result(val)
            """)
        found = cancel_safety.check_q02(ctx)
        assert _codes(found) == ["Q02"]
        assert "stranded" in found[0].message

    def test_base_exception_resolve_and_reraise_is_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class Pump:
                async def run(self, fut):
                    try:
                        val = await self._fetch()
                    except BaseException as e:
                        fut.set_exception(e)
                        raise
                    fut.set_result(val)
            """)
        assert cancel_safety.check_q02(ctx) == []

    def test_shared_slot_future_nobody_resolves(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class Reg:
                def register(self, key):
                    self._waiters[key] = (
                        asyncio.get_event_loop().create_future())
                    return self._waiters[key]
            """)
        found = cancel_safety.check_q02(ctx)
        assert _codes(found) == ["Q02"]
        assert "_waiters" in found[0].message

    def test_sibling_resolver_discharges_shared_slot(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class Reg:
                def register(self, key):
                    self._waiters[key] = (
                        asyncio.get_event_loop().create_future())
                    return self._waiters[key]

                def resolve(self, key, val):
                    self._waiters[key].set_result(val)
            """)
        assert cancel_safety.check_q02(ctx) == []


class TestCancelHandoff:
    """Q03: 'except Exception' around an await lets CancelledError
    skip a must-happen hand-off."""

    def test_exception_guard_over_handoff(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class Confirm:
                async def run(self, fut):
                    try:
                        val = await self._leader_confirm()
                        fut.set_result(val)
                    except Exception as e:
                        fut.set_exception(e)
            """)
        found = cancel_safety.check_q03(ctx)
        assert _codes(found) == ["Q03"]
        assert "CancelledError escapes this handler" in found[0].message

    def test_base_exception_split_is_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class Confirm:
                async def run(self, fut):
                    try:
                        val = await self._leader_confirm()
                        fut.set_result(val)
                    except BaseException as e:
                        fut.set_exception(e)
                        raise
            """)
        assert cancel_safety.check_q03(ctx) == []

    def test_finally_handoff_is_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class Confirm:
                async def run(self, fut):
                    val = None
                    try:
                        val = await self._leader_confirm()
                    except Exception:
                        pass
                    finally:
                        fut.set_result(val)
            """)
        assert cancel_safety.check_q03(ctx) == []


class TestHandoffSupervision:
    """Q04: a task spawned to perform a hand-off must be supervised
    or self-supervising."""

    def test_unsupervised_handoff_task(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class Runner:
                def kick(self):
                    asyncio.ensure_future(self._work())

                async def _work(self):
                    await self._compute()
                    self._batch["fired"] = True
            """)
        found = cancel_safety.check_q04(ctx)
        assert _codes(found) == ["Q04"]
        assert "_work" in found[0].message

    def test_done_callback_supervises(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class Runner:
                def kick(self):
                    t = asyncio.ensure_future(self._work())
                    t.add_done_callback(self._reap)

                async def _work(self):
                    await self._compute()
                    self._batch["fired"] = True
            """)
        assert cancel_safety.check_q04(ctx) == []

    def test_self_supervising_body_is_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class Runner:
                def kick(self):
                    asyncio.ensure_future(self._work())

                async def _work(self):
                    try:
                        await self._compute()
                    finally:
                        self._batch["fired"] = True
            """)
        assert cancel_safety.check_q04(ctx) == []


class TestCancelSuppression:
    """noqa / baseline plumbing works for the Q codes."""

    _Q01_SRC = """\
        import asyncio

        class Batcher:
            def __init__(self):
                self._fut = None

            def arm(self):
                self._fut = asyncio.get_event_loop().create_future()

            def fire(self, val):
                self._fut.set_result(val)

            async def join(self):
                return await self._fut{noqa}
        """

    def test_noqa_q01_suppresses(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text(textwrap.dedent(self._Q01_SRC.format(noqa="")))
        assert _codes(run_vet([str(p)], baseline_path=None).findings) \
            == ["Q01"]
        p.write_text(textwrap.dedent(
            self._Q01_SRC.format(noqa="  # noqa: Q01")))
        assert run_vet([str(p)], baseline_path=None).findings == []

    def test_baseline_suppresses_q02(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text(textwrap.dedent("""\
            import asyncio

            def f():
                fut = asyncio.get_event_loop().create_future()
                return 1
            """))
        unsuppressed = run_vet([str(p)], baseline_path=None)
        assert _codes(unsuppressed.findings) == ["Q02"]
        base = tmp_path / "baseline.txt"
        base.write_text("# justified: fixture\n"
                        + unsuppressed.findings[0].baseline_key() + "\n")
        result = run_vet([str(p)], baseline_path=base)
        assert result.findings == []
        assert result.baselined == 1 and result.rc == 0

    def test_stale_baseline_across_q_codes(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("x = 1\n")
        base = tmp_path / "baseline.txt"
        base.write_text("gone.py|Q01|old shield finding\n"
                        "gone.py|Q02|old resolution finding\n"
                        "gone.py|Q03|old guard finding\n"
                        "gone.py|Q04|old supervision finding\n")
        result = run_vet([str(p)], baseline_path=base)
        assert sorted(k.split("|")[1] for k in result.stale_baseline) \
            == ["Q01", "Q02", "Q03", "Q04"]
        assert result.rc == 0

    def test_real_server_is_q_clean(self):
        # the production file the tier was built against, post-fix
        p = REPO / "consul_tpu" / "server" / "server.py"
        ctx = FileCtx.load(p, "consul_tpu/server/server.py")
        assert cancel_safety.check(ctx) == []

    def test_prefix_confirm_batch_shape_is_caught(self, tmp_path):
        # the ADVICE r5 high finding, reduced: _run_confirm_batch
        # awaits its predecessor bare (Q01 — cancelling this runner
        # cancels the predecessor's shared future) under an
        # 'except Exception' guard whose continuation fires the batch
        # (Q03 — a CancelledError skips the hand-off and strands every
        # joiner).  This is the pre-fix server.py shape.
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            class Server:
                def __init__(self):
                    self._confirm_batches = {}
                    self._confirm_prev = {}

                async def _confirm_batched(self, key, runner):
                    b = self._confirm_batches.get(key)
                    if b is None or b["fired"]:
                        b = self._confirm_batches[key] = {
                            "fut": asyncio.get_event_loop()
                            .create_future(),
                            "fired": False}
                        asyncio.get_event_loop().create_task(
                            self._run_confirm_batch(key, b, runner))
                    return await asyncio.shield(b["fut"])

                async def _run_confirm_batch(self, key, b, runner):
                    try:
                        prev = self._confirm_prev.get(key)
                        if prev is not None and not prev.done():
                            await prev
                        b["fired"] = True
                        self._confirm_prev[key] = b["fut"]
                        result = await runner()
                        if not b["fut"].done():
                            b["fut"].set_result(result)
                    except Exception as exc:
                        if not b["fut"].done():
                            b["fut"].set_exception(exc)
            """)
        assert _codes(cancel_safety.check_q01(ctx)) == ["Q01"]
        assert _codes(cancel_safety.check_q03(ctx)) == ["Q03"]


# -- environment-gate union group (table_drift.check_env_gates) --------------


class TestEnvGates:
    """The CONSUL_TPU_* registry vs usage sites vs README table."""

    REAL_GATES = sorted(table_drift.ENV_GATE_SITES)

    def _gov(self, tmp_path, gates):
        src = "ENV_GATES = {\n" + "".join(
            '    "%s": "d",\n' % g for g in sorted(gates)) + "}\n"
        return _ctx(tmp_path, "consul_tpu/obs/envgates.py", src)

    def _readme(self, gates):
        return "".join("| `%s` | x |\n" % g for g in sorted(gates))

    def test_synced_registry_and_readme_are_clean(self, tmp_path):
        gov = self._gov(tmp_path, self.REAL_GATES)
        assert table_drift.check_env_gates(
            [gov], readme_text=self._readme(self.REAL_GATES)) == []

    def test_unregistered_literal_fires(self, tmp_path):
        gov = self._gov(tmp_path, self.REAL_GATES)
        user = _ctx(tmp_path, "consul_tpu/obs/extra.py", """\
            import os
            FLAG = os.environ.get("CONSUL_TPU_BOGUS_GATE")
            """)
        found = table_drift.check_env_gates(
            [gov, user], readme_text=self._readme(self.REAL_GATES))
        assert _codes(found) == ["K01"]
        assert found[0].line == 2
        assert "not registered" in found[0].message

    def test_dead_canonical_site_fires(self, tmp_path):
        # the journey reader is present but only reads one of its two
        # registered gates — the other is dead configuration
        gov = self._gov(tmp_path, self.REAL_GATES)
        site = _ctx(tmp_path, "consul_tpu/obs/journey.py", """\
            import os
            ON = os.environ.get("CONSUL_TPU_JOURNEY", "1")
            """)
        found = table_drift.check_env_gates(
            [gov, site], readme_text=self._readme(self.REAL_GATES))
        assert _codes(found) == ["K01"]
        assert "CONSUL_TPU_JOURNEY_BUDGET_MS" in found[0].message
        assert "dead configuration" in found[0].message

    def test_readme_missing_gate_fires(self, tmp_path):
        gov = self._gov(tmp_path, self.REAL_GATES)
        docs = self._readme(
            [g for g in self.REAL_GATES if g != "CONSUL_TPU_AUTOTUNE"])
        found = table_drift.check_env_gates([gov], readme_text=docs)
        assert _codes(found) == ["K01"]
        assert found[0].path == "README.md"
        assert "CONSUL_TPU_AUTOTUNE is registered" in found[0].message
        assert "never mentioned" in found[0].message

    def test_readme_stale_gate_fires(self, tmp_path):
        gov = self._gov(tmp_path, self.REAL_GATES)
        docs = self._readme(self.REAL_GATES) \
            + "| `CONSUL_TPU_NOT_A_GATE` | x |\n"
        found = table_drift.check_env_gates([gov], readme_text=docs)
        assert _codes(found) == ["K01"]
        assert found[0].line == len(docs.splitlines())
        assert "stale docs" in found[0].message

    def test_registry_site_mirror_divergence(self, tmp_path):
        # a registered gate with no declared canonical reader; the
        # fixture names are deliberately unregistered — exactly what
        # the project-wide literal sweep exists to flag
        extra = ["CONSUL_TPU_EXTRA_GATE"]  # noqa: K01 — fixture gate
        gov = self._gov(tmp_path, self.REAL_GATES + extra)
        docs = self._readme(self.REAL_GATES + extra)
        found = table_drift.check_env_gates([gov], readme_text=docs)
        assert _codes(found) == ["K01"]
        assert "no canonical reader" in found[0].message
        # and the converse: a declared reader whose gate vanished
        reduced = [g for g in self.REAL_GATES
                   if g != "CONSUL_TPU_DEV_OBS"]
        gov = self._gov(tmp_path / "b", reduced)
        found = table_drift.check_env_gates(
            [gov], readme_text=self._readme(reduced))
        assert _codes(found) == ["K01"]
        assert "missing from the ENV_GATES registry" in found[0].message

    def test_subset_without_registry_skips(self, tmp_path):
        user = _ctx(tmp_path, "consul_tpu/obs/other.py", "x = 1\n")
        assert table_drift.check_env_gates([user], readme_text="") == []

    def test_real_tree_registry_matches_sites(self):
        # the live contract: the shipped registry and the vet-side
        # mirror agree, and every declared reader file exists
        from consul_tpu.obs.envgates import ENV_GATES
        assert sorted(ENV_GATES) == self.REAL_GATES
        for site in set(table_drift.ENV_GATE_SITES.values()):
            assert (REPO / site).is_file(), site


# -- time guard (the `make vet` wall-time regression gate) -------------------


class TestTimeGuard:
    def test_prior_total_ms_sums_report(self, tmp_path):
        r = tmp_path / "vet_report.json"
        r.write_text(json.dumps(
            {"per_pass_ms": {"names": 10.0, "donation": 5.5}}))
        assert prior_total_ms(r) == 15.5

    def test_prior_total_ms_disarms_on_missing_or_bad(self, tmp_path):
        assert prior_total_ms(tmp_path / "nope.json") == 0.0
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        assert prior_total_ms(bad) == 0.0
        nolist = tmp_path / "nolist.json"
        nolist.write_text(json.dumps({"per_pass_ms": "oops"}))
        assert prior_total_ms(nolist) == 0.0

    def test_threshold_factor_and_slack(self):
        assert not time_guard_exceeded(0.0, 99999.0)   # first run: disarmed
        assert not time_guard_exceeded(1000.0, 1999.0)  # under 1.5x + slack
        assert time_guard_exceeded(1000.0, 2001.0)

    def test_slowest_passes_ranks(self):
        top = slowest_passes({"a": 5.0, "b": 20.0, "c": 10.0})
        assert top == [("b", 20.0), ("c", 10.0)]

    def test_guard_trips_end_to_end(self, tmp_path, capsys, monkeypatch):
        import tools.vet.driver as driver
        monkeypatch.setattr(driver, "TIME_GUARD_SLACK_MS", 0.0)
        p = tmp_path / "m.py"
        p.write_text("x = 1\n")
        report = tmp_path / "vet_report.json"
        report.write_text(json.dumps({"per_pass_ms": {"names": 0.0001}}))
        rc = vet_main([str(p), "--no-baseline",
                       "--report", str(report), "--time-guard"])
        assert rc == 2
        assert "time guard" in capsys.readouterr().err

    def test_guard_quiet_without_prior_report(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("x = 1\n")
        report = tmp_path / "vet_report.json"
        rc = vet_main([str(p), "--no-baseline",
                       "--report", str(report), "--time-guard"])
        assert rc == 0    # first run records a baseline, never trips


def _git(cwd, *args):
    subprocess.run(["git", *args], cwd=cwd, check=True,
                   capture_output=True)


class TestChangedMode:
    def test_expand_partners_pulls_group(self):
        all_paths = ["consul_tpu/gossip/params.py",
                     "consul_tpu/obs/devstats.py",
                     "bench.py", "tools/profile_kernel.py",
                     "consul_tpu/api/kv.py"]
        only = expand_partners({"consul_tpu/obs/devstats.py"}, all_paths)
        assert only == {"consul_tpu/gossip/params.py",
                        "consul_tpu/obs/devstats.py",
                        "bench.py", "tools/profile_kernel.py"}

    def test_expand_partners_leaves_loners(self):
        only = expand_partners({"consul_tpu/api/kv.py"},
                               ["consul_tpu/api/kv.py", "bench.py"])
        assert only == {"consul_tpu/api/kv.py"}

    def test_role_transition_partner_group(self):
        # A touch to the server (or the hotpath that drives lease
        # reads) must pull the raft core back under the T passes.
        all_paths = list(ROLE_TRANSITION_GROUP) + ["bench.py"]
        only = expand_partners({"consul_tpu/server/server.py"}, all_paths)
        assert set(ROLE_TRANSITION_GROUP) <= only
        assert "bench.py" not in only

    def test_changed_paths_and_only_filter(self, tmp_path, monkeypatch):
        _git(tmp_path, "init", "-q")
        defect = ("def f():\n    try:\n        return 1\n"
                  "    except Exception:\n        pass\n")
        (tmp_path / "a.py").write_text(defect)
        (tmp_path / "b.py").write_text("x = 1\n")
        _git(tmp_path, "add", ".")
        _git(tmp_path, "-c", "user.email=v@t", "-c", "user.name=v",
             "commit", "-q", "-m", "seed")
        (tmp_path / "b.py").write_text(defect)       # tracked, modified
        (tmp_path / "c.py").write_text(defect)       # untracked
        monkeypatch.chdir(tmp_path)
        changed = changed_paths()
        assert changed == {"b.py", "c.py"}
        result = run_vet(["."], baseline_path=None, only=changed)
        # a.py has the same defect but was not touched -> not vetted
        assert sorted({f.path for f in result.findings}) \
            == ["b.py", "c.py"]
        assert result.files == 2
        # partial runs cannot judge baseline staleness
        assert result.stale_baseline == []

    def test_exit_code_contract_unchanged(self, tmp_path, monkeypatch):
        _git(tmp_path, "init", "-q")
        (tmp_path / "a.py").write_text("x = 1\n")
        _git(tmp_path, "add", ".")
        _git(tmp_path, "-c", "user.email=v@t", "-c", "user.name=v",
             "commit", "-q", "-m", "seed")
        monkeypatch.chdir(tmp_path)
        assert vet_main([".", "--no-baseline", "--changed"]) == 0
        (tmp_path / "a.py").write_text("def f():\n    return undefined\n")
        assert vet_main([".", "--no-baseline", "--changed"]) == 1


class TestPassTimings:
    def test_per_pass_ms_recorded(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("x = 1\n")
        result = run_vet([str(p)], baseline_path=None)
        assert set(result.per_pass_ms) == set(result.per_pass)
        assert all(ms >= 0 for ms in result.per_pass_ms.values())
        assert "pallas-safety" in result.per_pass_ms
        assert "table-drift" in result.per_pass_ms
        assert "fork-safety" in result.per_pass_ms
        assert "interleave" in result.per_pass_ms
        assert "role-transition" in result.per_pass_ms

    def test_per_pass_ms_in_report(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("x = 1\n")
        report = tmp_path / "vet_report.json"
        vet_main([str(p), "--no-baseline", "--report", str(report)])
        data = json.loads(report.read_text())
        assert set(data["per_pass_ms"]) == set(data["per_pass"])

    def test_slowest_passes_printed(self, tmp_path, capsys):
        p = tmp_path / "m.py"
        p.write_text("x = 1\n")
        vet_main([str(p), "--no-baseline"])
        assert "slowest pass" in capsys.readouterr().err


class TestStaleBaselineListing:
    def test_exact_stale_lines_printed(self, tmp_path, capsys):
        p = tmp_path / "m.py"
        p.write_text("x = 1\n")
        base = tmp_path / "baseline.txt"
        base.write_text("gone.py|E02|no longer found\n")
        rc = vet_main([str(p), "--baseline", str(base)])
        err = capsys.readouterr().err
        assert rc == 0
        assert "stale baseline entry: gone.py|E02|no longer found" in err


# -- dynamic sanitizer harness (tools/vet/dyn.py) ----------------------------


class TestDynHarness:
    def test_evaluate_leaks_clean(self):
        assert vet_dyn.evaluate_leaks({
            "fd_start": 10, "fd_end": 12,
            "extra_threads": [], "asyncio_errors": []}) == []

    def test_evaluate_leaks_fd_growth(self):
        probs = vet_dyn.evaluate_leaks({
            "fd_start": 10, "fd_end": 200,
            "extra_threads": [], "asyncio_errors": []})
        assert probs and "fd leak" in probs[0]

    def test_evaluate_leaks_threads_and_asyncio(self):
        probs = vet_dyn.evaluate_leaks({
            "fd_start": 10, "fd_end": 10,
            "extra_threads": ["worker-3"],
            "asyncio_errors": ["Task was destroyed but it is pending!"]})
        assert len(probs) == 2
        assert "thread leak" in probs[0]
        assert "asyncio error-log" in probs[1]

    def test_evaluate_leaks_no_fd_accounting(self):
        # non-Linux boxes report -1; no false fd finding
        assert vet_dyn.evaluate_leaks({
            "fd_start": -1, "fd_end": -1,
            "extra_threads": [], "asyncio_errors": []}) == []

    def test_interleave_slice_files_exist(self):
        for t in vet_dyn.INTERLEAVE_SLICE:
            assert (REPO / t).is_file(), t

    def test_forced_interleave_switches_at_done_future(self, tmp_path):
        # With the shim, awaiting an already-done future is a real task
        # switch: coroutine b runs between a's read and a's write.
        (tmp_path / "test_forced.py").write_text(textwrap.dedent("""\
            import asyncio

            def test_switch_at_done_future_await():
                async def main():
                    order = []

                    async def a():
                        fut = asyncio.get_event_loop().create_future()
                        fut.set_result(1)
                        order.append("a:pre")
                        await fut
                        order.append("a:post")

                    async def b():
                        order.append("b")

                    await asyncio.gather(a(), b())
                    return order

                assert asyncio.run(main()) == ["a:pre", "b", "a:post"]
            """))
        env = dict(__import__("os").environ)
        env[vet_dyn.INTERLEAVE_ENV] = "1"
        env.pop(vet_dyn.NANS_ENV, None)
        env.pop(vet_dyn.REPORT_ENV, None)
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", str(tmp_path), "-q",
             "-p", "tools.vet.dyn", "-p", "no:cacheprovider"],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_vanilla_loop_does_not_switch_at_done_future(self, tmp_path):
        # The negative twin: without the env var the plugin leaves
        # asyncio alone, and a done-future await completes inline.
        (tmp_path / "test_vanilla.py").write_text(textwrap.dedent("""\
            import asyncio

            def test_no_switch_at_done_future_await():
                async def main():
                    order = []

                    async def a():
                        fut = asyncio.get_event_loop().create_future()
                        fut.set_result(1)
                        order.append("a:pre")
                        await fut
                        order.append("a:post")

                    async def b():
                        order.append("b")

                    await asyncio.gather(a(), b())
                    return order

                assert asyncio.run(main()) == ["a:pre", "a:post", "b"]
            """))
        env = dict(__import__("os").environ)
        env.pop(vet_dyn.INTERLEAVE_ENV, None)
        env.pop(vet_dyn.NANS_ENV, None)
        env.pop(vet_dyn.REPORT_ENV, None)
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", str(tmp_path), "-q",
             "-p", "tools.vet.dyn", "-p", "no:cacheprovider"],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_plugin_writes_session_report(self, tmp_path):
        (tmp_path / "test_tiny.py").write_text(
            "def test_ok():\n    assert 1 + 1 == 2\n")
        report = tmp_path / "dyn_report.json"
        env = dict(__import__("os").environ)
        env[vet_dyn.REPORT_ENV] = str(report)
        env.pop(vet_dyn.NANS_ENV, None)   # keep jax out of this run
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", str(tmp_path), "-q",
             "-p", "tools.vet.dyn", "-p", "no:cacheprovider"],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        data = json.loads(report.read_text())
        assert data["exitstatus"] == 0
        assert vet_dyn.evaluate_leaks(data) == []

    def test_cancel_injector_counts_only_victim_awaits(self):
        # k=2: the first noted await arms nothing, the second cancels
        # the victim; awaits by other tasks never advance the count
        async def main():
            inj = vet_dyn._CancelInjector(2)
            cancelled = []

            async def bystander():
                inj.note_await()   # not the victim: ignored

            async def victim_body():
                inj.victim = asyncio.current_task()
                inj.note_await()
                assert not inj.fired and inj.seen == 1
                inj.note_await()
                assert inj.fired and inj.seen == 2
                try:
                    await asyncio.sleep(1)
                except asyncio.CancelledError:
                    cancelled.append(True)
                    raise

            await bystander()
            assert inj.seen == 0
            t = asyncio.ensure_future(victim_body())
            await asyncio.gather(t, return_exceptions=True)
            assert cancelled and t.cancelled()

        asyncio.run(main())

    def test_cancel_scenarios_cover_the_three_slices(self):
        names = [name for name, _victims, _fn in vet_dyn._CANCEL_SCENARIOS]
        assert names == ["confirm-batch", "reconcile-flush",
                         "blocking-query"]
        assert vet_dyn.CANCEL_ENV == "CONSUL_TPU_DYN_CANCEL"

    def test_cancel_injection_leg_is_clean(self):
        # the full sweep over the real production objects: every
        # (scenario, victim, k) combination must leave no future
        # pending and no batch unfired
        env = dict(__import__("os").environ)
        env[vet_dyn.CANCEL_ENV] = "1"
        proc = subprocess.run(
            [sys.executable, "-m", "tools.vet.dyn", "--cancel"],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "cancel-injection leg clean" in proc.stderr
        for name, victims, _fn in vet_dyn._CANCEL_SCENARIOS:
            for victim in victims:
                assert f"cancel[{name}/{victim}]: swept" in proc.stderr


# -- meta: the analyzer meets its own standard -------------------------------


class TestSelfAnalysis:
    def test_tools_vet_is_clean_under_itself(self):
        result = run_vet([str(REPO / "tools" / "vet")], baseline_path=None)
        assert result.parse_errors == []
        assert result.findings == []
