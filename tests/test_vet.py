"""tools/vet — the six-pass static analyzer.

Each pass gets one known-bad snippet (the planted defect it must
catch) and one clean snippet (the idiomatic fix it must NOT flag),
plus the suppression machinery (``# noqa: CODE``, blanket ``# noqa``,
baseline) and the exit-code contract.  The meta-test at the bottom
holds the analyzer to its own standard.
"""

import textwrap
from pathlib import Path

from tools.vet import async_safety, exceptions, names, tracer_purity
from tools.vet import wire_schema
from tools.vet.core import FileCtx, parse_noqa
from tools.vet.driver import main as vet_main
from tools.vet.driver import run_vet

REPO = Path(__file__).resolve().parent.parent


def _ctx(tmp_path, name, src):
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return FileCtx.load(p, p.as_posix())


def _codes(findings):
    return [f.code for f in findings]


# -- names (the legacy pyvet passes on the new walker) -----------------------


class TestNames:
    def test_undefined_name(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            def f():
                return not_defined_anywhere
            """)
        assert "N01" in _codes(names.check(ctx))

    def test_unused_import(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import os
            import sys

            print(sys.argv)
            """)
        found = names.check(ctx)
        assert _codes(found) == ["N02"]
        assert "os" in found[0].message

    def test_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import os

            def f():
                return os.getpid()
            """)
        assert names.check(ctx) == []


# -- async-safety ------------------------------------------------------------


class TestAsyncSafety:
    def test_a01_unawaited_coroutine(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            async def work():
                pass

            async def caller():
                work()
            """)
        assert "A01" in _codes(async_safety.check(ctx))

    def test_a01_self_method(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            class A:
                async def start(self):
                    pass

                def boot(self):
                    self.start()
            """)
        assert "A01" in _codes(async_safety.check(ctx))

    def test_a01_other_object_not_flagged(self, tmp_path):
        # self.local.start() must NOT match A.start — the sync method
        # of another object merely shares the name.
        ctx = _ctx(tmp_path, "m.py", """\
            class A:
                async def start(self):
                    pass

                def boot(self):
                    self.local.start()
            """)
        assert async_safety.check(ctx) == []

    def test_a02_dropped_task(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            async def main():
                asyncio.create_task(asyncio.sleep(1))
            """)
        assert "A02" in _codes(async_safety.check(ctx))

    def test_a02_task_set_pattern_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            tasks = set()

            async def main():
                t = asyncio.create_task(asyncio.sleep(1))
                tasks.add(t)
                t.add_done_callback(tasks.discard)
            """)
        assert async_safety.check(ctx) == []

    def test_a03_blocking_call(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import time

            async def f():
                time.sleep(1)
            """)
        assert "A03" in _codes(async_safety.check(ctx))

    def test_a03_through_from_import(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            from time import sleep

            async def f():
                sleep(1)
            """)
        assert "A03" in _codes(async_safety.check(ctx))

    def test_a03_nested_sync_def_clean(self, tmp_path):
        # a plain def nested in a coroutine may run in an executor
        ctx = _ctx(tmp_path, "m.py", """\
            import time

            async def f():
                def worker():
                    time.sleep(1)
                return worker
            """)
        assert async_safety.check(ctx) == []

    def test_a04_threading_lock(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import threading

            lock = threading.Lock()

            async def f():
                with lock:
                    pass
            """)
        assert "A04" in _codes(async_safety.check(ctx))

    def test_a04_asyncio_lock_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            lock = asyncio.Lock()

            async def f():
                async with lock:
                    pass
            """)
        assert async_safety.check(ctx) == []


# -- tracer-purity -----------------------------------------------------------


class TestTracerPurity:
    def test_j01_float_on_traced(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import jax

            @jax.jit
            def f(x):
                return float(x) + 1.0
            """)
        assert "J01" in _codes(tracer_purity.check(ctx))

    def test_j01_item(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import jax

            @jax.jit
            def f(x):
                return x.sum().item()
            """)
        assert "J01" in _codes(tracer_purity.check(ctx))

    def test_j01_static_argname_exempt(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import functools

            import jax

            @functools.partial(jax.jit, static_argnames=("n",))
            def f(x, n):
                return x * int(n)
            """)
        assert tracer_purity.check(ctx) == []

    def test_j02_numpy_in_trace(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return np.cumsum(x)
            """)
        assert "J02" in _codes(tracer_purity.check(ctx))

    def test_j02_dtype_constructor_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import jax
            import jax.numpy as jnp
            import numpy as np

            @jax.jit
            def f(x):
                return jnp.cumsum(x.astype(np.int32))
            """)
        assert tracer_purity.check(ctx) == []

    def test_j03_time_read(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import time

            import jax

            @jax.jit
            def f(x):
                return x + time.monotonic()
            """)
        assert "J03" in _codes(tracer_purity.check(ctx))

    def test_j03_reaches_helpers(self, tmp_path):
        # the call graph extends the root set to module helpers
        ctx = _ctx(tmp_path, "m.py", """\
            import random

            import jax

            def helper(x):
                return x * random.random()

            @jax.jit
            def f(x):
                return helper(x)
            """)
        assert "J03" in _codes(tracer_purity.check(ctx))

    def test_j04_scan_body_mutation(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import jax
            from jax import lax

            seen = []

            def body(carry, x):
                seen.append(x)
                return carry + x, x

            def run(xs):
                return lax.scan(body, 0, xs)
            """)
        assert "J04" in _codes(tracer_purity.check(ctx))

    def test_j04_carry_threading_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import jax
            from jax import lax

            def body(carry, x):
                acc = carry + x
                return acc, acc

            def run(xs):
                return lax.scan(body, 0, xs)
            """)
        assert tracer_purity.check(ctx) == []

    def test_non_jax_module_skipped(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import time

            def f(x):
                return float(x) + time.time()
            """)
        assert tracer_purity.check(ctx) == []


# -- wire-schema -------------------------------------------------------------


class TestWireSchema:
    def test_w01_w02_function_pair(self, tmp_path):
        ctx = _ctx(tmp_path, "codec.py", """\
            def ping_to_wire(m):
                return {"a": m.a, "b": m.b}

            def ping_from_wire(d):
                return (d["a"], d.get("c"))
            """)
        found = wire_schema.check_project(
            [ctx], modules=("codec.py",), envelope_groups=())
        assert _codes(found) == ["W01", "W02"]
        assert "'b'" in found[0].message   # written, never read
        assert "'c'" in found[1].message   # read, never written

    def test_class_pair_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "codec.py", """\
            class Ping:
                def to_wire(self):
                    return {"a": self.a}

                @classmethod
                def from_wire(cls, d):
                    return cls(d.get("a"))
            """)
        assert wire_schema.check_project(
            [ctx], modules=("codec.py",), envelope_groups=()) == []

    def test_one_sided_unit_skipped(self, tmp_path):
        # the peer lives outside the scanned surface — no findings
        ctx = _ctx(tmp_path, "codec.py", """\
            def ping_to_wire(m):
                return {"a": m.a}
            """)
        assert wire_schema.check_project(
            [ctx], modules=("codec.py",), envelope_groups=()) == []

    def test_envelope_group_cross_file(self, tmp_path):
        srv = _ctx(tmp_path, "srv.py", """\
            def reply(w, body):
                w.send({"Seq": 1, "Error": "", "Extra": body})
            """)
        cli = _ctx(tmp_path, "cli.py", """\
            def read(d):
                return d["Seq"], d.get("Error"), d.get("Missing")
            """)
        found = wire_schema.check_project(
            [srv, cli], modules=("srv.py", "cli.py"),
            envelope_groups=(("env", ("srv.py", "cli.py")),))
        assert _codes(found) == ["W02", "W01"]  # sorted by path
        assert "'Missing'" in found[0].message
        assert "'Extra'" in found[1].message

    def test_repo_wire_surface_clean(self):
        roots = [str(REPO / m) for m in wire_schema.WIRE_MODULES]
        result = run_vet(roots, passes=["wire-schema"], baseline_path=None)
        assert result.findings == []


# -- exception-hygiene -------------------------------------------------------


class TestExceptionHygiene:
    def test_e01_bare_except(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            def f():
                try:
                    return 1
                except:
                    return 0
            """)
        assert "E01" in _codes(exceptions.check(ctx))

    def test_e02_silent_broad(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            def f():
                try:
                    return 1
                except Exception:
                    pass
            """)
        assert "E02" in _codes(exceptions.check(ctx))

    def test_e02_handled_clean(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import logging

            def f():
                try:
                    return 1
                except Exception:
                    logging.exception("f failed")
            """)
        assert exceptions.check(ctx) == []

    def test_e03_tuple_with_cancelled(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            async def f(task):
                try:
                    await task
                except (asyncio.CancelledError, ValueError):
                    pass
            """)
        assert "E03" in _codes(exceptions.check(ctx))

    def test_e03_cancel_only_exempt(self, tmp_path):
        # the deliberate cancel-then-await idiom
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            async def f(task):
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
            """)
        assert exceptions.check(ctx) == []

    def test_e03_reraise_exempt(self, tmp_path):
        ctx = _ctx(tmp_path, "m.py", """\
            import asyncio

            async def f(task):
                try:
                    await task
                except BaseException:
                    task = None
                    raise
            """)
        assert _codes(exceptions.check(ctx)) == []

    def test_e03_sync_function_exempt(self, tmp_path):
        # no coroutine, no cancellation to swallow (still E02 though)
        ctx = _ctx(tmp_path, "m.py", """\
            def f():
                try:
                    return 1
                except BaseException:
                    pass
            """)
        assert _codes(exceptions.check(ctx)) == ["E02"]


# -- suppression: noqa + baseline --------------------------------------------


class TestSuppression:
    def test_parse_noqa_forms(self):
        noqa = parse_noqa("x = 1  # noqa\ny = 2  # noqa: A02, e03\nz = 3\n")
        assert noqa[1] is None            # blanket
        assert noqa[2] == {"A02", "E03"}  # codes, case-folded
        assert 3 not in noqa

    def test_noqa_code_suppresses(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text(textwrap.dedent("""\
            import asyncio

            async def main():
                asyncio.create_task(asyncio.sleep(1))  # noqa: A02
            """))
        result = run_vet([str(p)], baseline_path=None)
        assert result.findings == []

    def test_noqa_wrong_code_does_not_suppress(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text(textwrap.dedent("""\
            import asyncio

            async def main():
                asyncio.create_task(asyncio.sleep(1))  # noqa: E02
            """))
        result = run_vet([str(p)], baseline_path=None)
        assert _codes(result.findings) == ["A02"]

    def test_blanket_noqa_suppresses_everything(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text(textwrap.dedent("""\
            import asyncio

            async def main():
                asyncio.create_task(asyncio.sleep(1))  # noqa
            """))
        result = run_vet([str(p)], baseline_path=None)
        assert result.findings == []

    def test_baseline_suppresses_and_counts(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("def f():\n    try:\n        return 1\n"
                     "    except Exception:\n        pass\n")
        unsuppressed = run_vet([str(p)], baseline_path=None)
        assert _codes(unsuppressed.findings) == ["E02"]
        base = tmp_path / "baseline.txt"
        base.write_text("# justified: fixture\n"
                        + unsuppressed.findings[0].baseline_key() + "\n")
        result = run_vet([str(p)], baseline_path=base)
        assert result.findings == []
        assert result.baselined == 1
        assert result.rc == 0

    def test_stale_baseline_entry_reported(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("x = 1\n")
        base = tmp_path / "baseline.txt"
        base.write_text("gone.py|E02|no longer found\n")
        result = run_vet([str(p)], baseline_path=base)
        assert result.stale_baseline == ["gone.py|E02|no longer found"]

    def test_write_baseline_roundtrip(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("def f():\n    try:\n        return 1\n"
                     "    except Exception:\n        pass\n")
        base = tmp_path / "baseline.txt"
        first = run_vet([str(p)], baseline_path=base, update_baseline=True)
        assert first.findings == [] and first.baselined == 1
        again = run_vet([str(p)], baseline_path=base)
        assert again.rc == 0 and again.stale_baseline == []


# -- exit codes (the `make vet` contract) ------------------------------------


class TestExitCodes:
    def test_rc0_clean(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("x = 1\n")
        assert vet_main([str(p), "--no-baseline"]) == 0

    def test_rc1_findings(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("def f():\n    try:\n        return 1\n"
                     "    except:\n        pass\n")
        assert vet_main([str(p), "--no-baseline"]) == 1

    def test_rc2_parse_error(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("def f(:\n")
        assert vet_main([str(p), "--no-baseline"]) == 2

    def test_rc2_unknown_pass(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("x = 1\n")
        assert vet_main([str(p), "--passes", "nope"]) == 2

    def test_pass_subset_runs_only_that_pass(self, tmp_path):
        p = tmp_path / "m.py"
        p.write_text("import os\n\n\ndef f():\n    try:\n        return 1\n"
                     "    except:\n        pass\n")
        result = run_vet([str(p)], passes=["names"], baseline_path=None)
        assert _codes(result.findings) == ["N02"]  # E01 pass not selected

    def test_legacy_pyvet_cli_still_names_only(self, tmp_path):
        from tools import pyvet
        p = tmp_path / "m.py"
        p.write_text("def f():\n    try:\n        return 1\n"
                     "    except:\n        pass\n")
        assert pyvet.main([str(p)]) == 0  # E01 is not a legacy pass


# -- meta: the analyzer meets its own standard -------------------------------


class TestSelfAnalysis:
    def test_tools_vet_is_clean_under_itself(self):
        result = run_vet([str(REPO / "tools" / "vet")], baseline_path=None)
        assert result.parse_errors == []
        assert result.findings == []
