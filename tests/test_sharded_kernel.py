"""Sharded-kernel equivalence: GSPMD over the 8-device mesh must be a
pure execution-strategy change.

The driver's ``dryrun_multichip`` proves the sharded multi-DC round
*compiles and runs*; this tier proves it computes THE SAME THING —
every state leaf bit-identical to the single-device run over enough
rounds to cross probe ticks, suspicion timeouts, dead declarations,
slot GC, and cross-DC event bridging.  A kernel change that breaks
under GSPMD (e.g. an op whose sharding lowers to a collective with
different semantics) fails here instead of at the driver.

Shardings mirror ``__graft_entry__.dryrun_multichip`` exactly: LAN
per-node arrays sharded on the node axis, slot registers + WAN pool
replicated.
"""


import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consul_tpu.gossip.kernel import NEVER
from consul_tpu.gossip.multidc import (MultiDCState, fire_in_dc,
                                       init_multidc, make_params,
                                       multidc_round)

# Enough rounds to cross probe ticks, the Lifeguard suspicion minimum
# (~55 rounds at n=512), dead declaration, and slot GC.
ROUNDS = 96


def _make_inputs(n_lan):
    p = make_params(n_dcs=2, n_lan=n_lan, n_servers=2, event_slots=4)
    state = init_multidc(p)
    state = fire_in_dc(state, dc=0, node=3, p=p)
    key = jax.random.PRNGKey(0)
    # Failures early enough that dead declarations + slot GC + the
    # serfHealth-style event bridge all happen inside ROUNDS.
    lan_fail = jnp.full((p.n_dcs, p.n_lan), NEVER, jnp.int32).at[0, 4:8].set(2)
    wan_fail = jnp.full((p.n_dcs * p.n_servers,), NEVER, jnp.int32)
    return p, state, key, lan_fail, wan_fail


def _shardings(mesh, state):
    node2 = NamedSharding(mesh, P(None, "nodes"))        # [D, N]
    node3 = NamedSharding(mesh, P(None, None, "nodes"))  # [D, S|E, N]
    rep = NamedSharding(mesh, P())
    lan_shard = dict(
        round=rep, heard=node3, slot_node=rep, slot_phase=rep,
        slot_inc=rep, slot_start=rep, slot_nsusp=rep, slot_dead_round=rep,
        slot_of_node=node2, incarnation=node2, member=node2,
        drops=rep, n_detected=rep, sum_detect_rounds=rep,
        n_false_dead=rep, n_refuted=rep)
    lan_ev_shard = dict(
        round=rep, has=node3, slot_used=rep, ltime=rep, origin=rep,
        start_round=rep, node_ltime=node2, n_seen=rep, drops=rep)
    rep_tree = lambda x: jax.tree.map(lambda _: rep, x)
    return MultiDCState(
        lan=type(state.lan)(**lan_shard),
        lan_events=type(state.lan_events)(**lan_ev_shard),
        wan=rep_tree(state.wan),
        wan_events=rep_tree(state.wan_events),
    ), node2, rep


@pytest.mark.slow
@pytest.mark.timeout_s(900)
def test_sharded_multidc_round_bit_identical():
    n_dev = 8
    devices = jax.devices()[:n_dev]
    assert len(devices) == n_dev, "conftest must provide the 8-device CPU mesh"
    mesh = Mesh(np.array(devices), ("nodes",))

    p, state0, key, lan_fail, wan_fail = _make_inputs(n_lan=64 * n_dev)

    def run_n(state, k, lf, wf):
        def body(st, _):
            return multidc_round(st, k, lf, wf, p=p), None
        return jax.lax.scan(body, state, None, length=ROUNDS)[0]

    # Single-device reference run.
    ref = jax.device_get(jax.jit(run_n)(state0, key, lan_fail, wan_fail))

    # Sharded run: identical inputs placed under the dryrun's shardings.
    shardings, node2, rep = _shardings(mesh, state0)
    runN = jax.jit(run_n,
                   in_shardings=(shardings, rep, node2, rep),
                   out_shardings=shardings)
    sh = jax.device_get(runN(
        jax.device_put(state0, shardings),
        jax.device_put(key, rep),
        jax.device_put(lan_fail, node2),
        jax.device_put(wan_fail, rep)))

    leaves_ref, treedef_ref = jax.tree.flatten(ref)
    leaves_sh, treedef_sh = jax.tree.flatten(sh)
    assert treedef_ref == treedef_sh
    paths = jax.tree_util.tree_flatten_with_path(ref)[0]
    for (path, a), b in zip(paths, leaves_sh):
        name = jax.tree_util.keystr(path)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"leaf {name} diverged")

    # The run must have exercised the interesting paths, or equality
    # proves nothing: failures detected and events seen cross-DC.
    assert int(np.asarray(ref.lan.n_detected).sum()) >= 1
    assert int(np.asarray(ref.wan_events.n_seen).sum()) >= 0
