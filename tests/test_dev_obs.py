"""Device/kernel observatory tests (obs/devstats.py).

Unit coverage for the env gate, the dispatch-latency hists + rounds/s
EWMA, compile/cache counters, cost_analysis ingestion (both jax return
shapes), the shared roofline derivation, CPU degradation (no
memory_stats -> the HBM gauges are absent, not zero), strict
tools/check_prom validation of the rendered families, the
/v1/agent/self stats rows, and the bundle manifest contract — plus
slow live-plane legs for the enabled and compiled-out
(CONSUL_TPU_DEV_OBS=0) postures.
"""

from __future__ import annotations

import asyncio

import pytest

from consul_tpu.agent import bundle
from consul_tpu.obs import devstats
from consul_tpu.obs.devstats import DevStats
from consul_tpu.obs.prom import render_prometheus
from consul_tpu.version import VERSION
from tools.check_prom import _iter_series, check_text


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


# -- env gate ---------------------------------------------------------------


def test_enabled_default_and_off_values(monkeypatch):
    monkeypatch.delenv("CONSUL_TPU_DEV_OBS", raising=False)
    assert devstats.enabled()
    for off in ("0", "false", "no", "FALSE", "No"):
        monkeypatch.setenv("CONSUL_TPU_DEV_OBS", off)
        assert not devstats.enabled()
    for on in ("1", "true", "yes", ""):
        monkeypatch.setenv("CONSUL_TPU_DEV_OBS", on)
        assert devstats.enabled()


def test_plane_carries_no_observatory_before_start():
    """The hot-path contract: every hook guards on ``_dev is not None``
    and a fresh (un-started) plane carries None."""
    from consul_tpu.gossip.plane import GossipPlane, PlaneConfig
    plane = GossipPlane(PlaneConfig(bind_port=0, capacity=8, slots=8))
    assert plane._dev is None


# -- dispatch hists + EWMA --------------------------------------------------


def test_dispatch_hist_observe_and_family():
    d = DevStats()
    d.note_dispatch("round_step", 2.0, 4, now=1.0)
    d.note_dispatch("round_step", 3.0, 4, now=2.0)
    d.note_drain(0.4)
    fam = d.dispatch["round_step"].family()
    assert fam["name"] == "consul_kernel_dispatch_ms"
    assert fam["count"] == 2
    assert fam["sum"] == pytest.approx(5.0)
    assert d.dispatch["drain"].count == 1
    # all four classes exist from construction (full dashboard schema)
    assert set(d.dispatch) == set(devstats.DISPATCH_CLASSES)


def test_dispatch_unknown_class_autovivifies():
    d = DevStats()
    d.note_dispatch("pallas_fused", 1.0, 4, now=1.0)
    assert d.dispatch["pallas_fused"].count == 1


def test_ewma_from_inter_dispatch_wall_time():
    d = DevStats()
    # first dispatch: no prior timestamp -> no rate yet
    d.note_dispatch("round_step", 1.0, 4, now=10.0)
    assert d.rounds_per_sec_ewma == 0.0
    # 4 rounds in 0.1s -> 40 rounds/s seeds the EWMA exactly
    d.note_dispatch("round_step", 1.0, 4, now=10.1)
    assert d.rounds_per_sec_ewma == pytest.approx(40.0)
    # a slower sample moves it toward 20 by alpha
    d.note_dispatch("round_step", 1.0, 4, now=10.3)
    assert d.rounds_per_sec_ewma == pytest.approx(40.0 + 0.2 * (20.0 - 40.0))


def test_drain_contributes_no_ewma():
    d = DevStats()
    d.note_dispatch("round_step", 1.0, 4, now=1.0)
    d.note_dispatch("round_step", 1.0, 4, now=1.1)
    before = d.rounds_per_sec_ewma
    d.note_drain(5.0)
    assert d.rounds_per_sec_ewma == before


# -- compile telemetry ------------------------------------------------------


def test_compile_counters_and_wall_times():
    d = DevStats()
    d.note_compile("plane_dispatch", 1.5, cache_hit=False)
    d.note_compile("event_dispatch", 0.2, cache_hit=True)
    d.note_compile("unknown_cache", 0.1, cache_hit=None)
    assert d.cache_hits == 1 and d.cache_misses == 1
    assert d.compile_wall_s == {"plane_dispatch": 1.5,
                                "event_dispatch": 0.2,
                                "unknown_cache": 0.1}


def test_cache_entries_counts_and_degrades(tmp_path):
    assert devstats.cache_entries("") is None
    assert devstats.cache_entries(str(tmp_path / "missing")) is None
    d = tmp_path / "cache"
    d.mkdir()
    assert devstats.cache_entries(str(d)) == 0
    (d / "a").write_text("x")
    (d / "b").write_text("y")
    assert devstats.cache_entries(str(d)) == 2


def test_note_cost_accepts_both_jax_shapes():
    d = DevStats()
    # Lowered.cost_analysis() -> dict with "bytes accessed" (space!)
    d.note_cost("lowered", {"flops": 1e6, "bytes accessed": 5e6}, steps=4)
    assert d.cost["lowered"] == {"flops": 1e6, "bytes_accessed": 5e6,
                                 "steps": 4.0}
    # Compiled.cost_analysis() -> one-element list of dicts
    d.note_cost("compiled", [{"flops": 2.0, "bytes_accessed": 8.0}])
    assert d.cost["compiled"] == {"flops": 2.0, "bytes_accessed": 8.0}
    # garbage shapes are ignored, never raise (best-effort contract)
    d.note_cost("junk", None)
    d.note_cost("junk", "nope")
    d.note_cost("junk", [])
    d.note_cost("junk", {"neither": 1})
    assert "junk" not in d.cost


# -- roofline derivation ----------------------------------------------------


def test_roofline_utilization_math():
    # 1 GB/round at 92.5 rounds/s = 92.5 GB/s over 185 GB/s = 0.5
    util = devstats.roofline_utilization(1e9, 92.5)
    assert util == pytest.approx(0.5)
    assert devstats.roofline_utilization(0.0, 10.0) is None
    assert devstats.roofline_utilization(1e9, 0.0) is None
    assert devstats.roofline_utilization(1e9, 10.0, ceiling_gbps=0) is None


def test_dense_bytes_per_round_matches_section_1c():
    assert devstats.dense_bytes_per_round(64, 1_000_000) == pytest.approx(
        devstats.DENSE_PASSES_PER_ROUND * 64 * 1_000_000)


def test_bytes_per_round_prefers_cost_analysis_over_analytic():
    d = DevStats()
    assert d.bytes_per_round() == (None, "unknown")
    d.set_session(slots=64, n=1000, steps_per_dispatch=4)
    bpr, src = d.bytes_per_round()
    assert src == "dense"
    assert bpr == pytest.approx(devstats.dense_bytes_per_round(64, 1000))
    # a lowered estimate for a 4-round dispatch refines it, per-round
    d.note_cost("plane_dispatch", {"bytes accessed": 4e6}, steps=4)
    bpr, src = d.bytes_per_round()
    assert src == "cost_analysis"
    assert bpr == pytest.approx(1e6)


def test_roofline_gauge_wire_shape():
    d = DevStats()
    d.set_session(slots=64, n=1000, steps_per_dispatch=4)
    d.note_dispatch("round_step", 1.0, 4, now=1.0)
    d.note_dispatch("round_step", 1.0, 4, now=1.1)
    roof = d.roofline()
    assert roof["ceiling_gbps"] == devstats.EFFECTIVE_HBM_GBPS
    assert roof["bytes_source"] == "dense"
    # the wire value is rounded to 6 decimals
    assert roof["utilization"] == pytest.approx(
        devstats.dense_bytes_per_round(64, 1000) * roof["rounds_per_sec_ewma"]
        / (devstats.EFFECTIVE_HBM_GBPS * 1e9), abs=1e-6)


# -- device telemetry (CPU degradation) -------------------------------------


def test_device_rows_cpu_has_census_but_no_hbm():
    rows = devstats.device_rows()
    assert rows, "jax is available in the test env"
    for row in rows:
        assert isinstance(row["id"], int)
        assert row["platform"] == "cpu"
        # CPU memory_stats() is None -> HBM keys ABSENT, not zero
        assert "hbm_bytes_in_use" not in row
        assert "hbm_bytes_limit" not in row
        assert isinstance(row["live_buffers"], int)
        assert isinstance(row["live_buffer_bytes"], int)


def test_sample_devices_caches_rows():
    d = DevStats()
    assert d._device_rows == []
    d.sample_devices()
    assert d._device_rows and d._device_sampled_at > 0


# -- exposition -------------------------------------------------------------


def _populated() -> DevStats:
    d = DevStats()
    d.set_session(slots=64, n=1000, steps_per_dispatch=4)
    d.note_compile("plane_dispatch", 1.2, cache_hit=False)
    d.note_cost("plane_dispatch", {"flops": 1e6, "bytes accessed": 4e6},
                steps=4)
    d.note_dispatch("round_step", 2.0, 4, now=1.0)
    d.note_dispatch("round_step", 2.5, 4, now=1.1)
    d.note_drain(0.3)
    d.sample_devices()
    return d


def test_prom_families_render_strict_clean():
    hists, gauges, counters = _populated().prom_families()
    text = render_prometheus(
        [], histograms=hists,
        labeled_gauges=gauges + devstats.build_info_families("tpu"),
        labeled_counters=counters)
    assert check_text(text) == [], check_text(text)
    names = {n for n, _ in _iter_series(text)}
    for want in ("consul_kernel_dispatch_ms_bucket",
                 "consul_kernel_rounds_per_sec",
                 "consul_kernel_compile_wall_seconds",
                 "consul_kernel_cost_bytes_accessed",
                 "consul_kernel_roofline_utilization",
                 "consul_kernel_dispatches_total",
                 "consul_kernel_compile_cache_hits_total",
                 "consul_kernel_compile_cache_misses_total",
                 "consul_device_live_buffers",
                 "consul_build_info", "consul_up"):
        assert want in names, f"missing {want}"
    # CPU: the HBM families must be absent, not zero-valued
    assert "consul_device_hbm_bytes_in_use" not in names


def test_dispatch_ladders_render_all_classes_before_traffic():
    hists, _, counters = DevStats().prom_families()
    assert len(hists) == len(devstats.DISPATCH_CLASSES)
    disp = next(c for c in counters
                if c["name"] == "consul_kernel_dispatches_total")
    assert {lbl["class"] for lbl, _ in disp["rows"]} == set(
        devstats.DISPATCH_CLASSES)


# -- /v1/agent/self rows + build info ---------------------------------------


def test_stats_rows_from_wire():
    d = _populated()
    wire = d.wire()
    wire["enabled"] = True
    rows = devstats.stats_rows(wire)
    assert rows["enabled"] == "true"
    assert rows["dispatches"] == "3"
    assert rows["compile_cache_misses"] == "1"
    assert float(rows["rounds_per_sec_ewma"]) > 0
    # disabled plane -> single row; no frame at all -> no rows
    assert devstats.stats_rows({"enabled": False}) == {"enabled": "false"}
    assert devstats.stats_rows({}) == {}


def test_build_info_contents():
    bi = devstats.build_info("tpu")
    assert bi["version"] == VERSION
    assert bi["backend"] == "tpu"
    assert bi["jax_version"] not in ("", None)
    fams = devstats.build_info_families("tpu")
    assert [f["name"] for f in fams] == ["consul_build_info", "consul_up"]
    assert fams[0]["rows"][0] == (bi, 1.0)
    assert fams[1]["rows"][0] == ({}, 1.0)


def test_bundle_sections_carry_device():
    assert "device" in bundle.SECTIONS


# -- live plane legs (kernel compile; slow tier) ----------------------------


@pytest.mark.slow
@pytest.mark.timeout_s(300)
def test_live_plane_observatory_enabled(loop):
    """A started plane carries a populated observatory: compile wall
    times from warmup, dispatch hists after a few ticks, and a
    check_prom-clean ``device`` frame."""
    from consul_tpu.gossip.plane import GossipPlane, PlaneConfig

    async def body():
        plane = GossipPlane(PlaneConfig(
            bind_port=0, capacity=8, slots=8, gossip_interval_s=0.02,
            suspicion_mult=1.0, hb_lapse_s=0.3))
        await plane.start()
        try:
            assert plane._dev is not None
            assert "plane_dispatch" in plane._dev.compile_wall_s
            deadline = asyncio.get_event_loop().time() + 20.0
            while (plane._dev.dispatch["round_step"].count == 0
                   and asyncio.get_event_loop().time() < deadline):
                await asyncio.sleep(0.05)
            wire = plane._device_wire()
            assert wire["enabled"] is True
            assert wire["dispatch"]["round_step"]["count"] > 0
            fams = wire["families"]
            text = render_prometheus(
                [], histograms=fams["histograms"],
                labeled_gauges=fams["gauges"],
                labeled_counters=fams["counters"])
            assert check_text(text) == [], check_text(text)
        finally:
            await plane.stop()
    loop.run_until_complete(body())


@pytest.mark.slow
@pytest.mark.timeout_s(300)
def test_live_plane_compiled_out(loop, monkeypatch):
    """CONSUL_TPU_DEV_OBS=0: the plane starts and runs with _dev None
    (every hook reduced to one attribute test) and the device frame
    reports enabled=false with no telemetry keys."""
    monkeypatch.setenv("CONSUL_TPU_DEV_OBS", "0")
    from consul_tpu.gossip.plane import GossipPlane, PlaneConfig

    async def body():
        plane = GossipPlane(PlaneConfig(
            bind_port=0, capacity=8, slots=8, gossip_interval_s=0.02,
            suspicion_mult=1.0, hb_lapse_s=0.3))
        await plane.start()
        try:
            assert plane._dev is None
            await asyncio.sleep(0.3)  # dispatches run with hooks off
            wire = plane._device_wire()
            assert wire["enabled"] is False
            assert "dispatch" not in wire
        finally:
            await plane.stop()
    loop.run_until_complete(body())


def test_devstats_module_never_imports_jax_at_module_level():
    """The agent process renders device payloads without a kernel: the
    module source must keep jax imports inside functions."""
    import inspect
    src = inspect.getsource(devstats)
    for line in src.splitlines():
        # only column-0 imports are module-level; the lazy in-function
        # `import jax` in device_rows() is the sanctioned exception
        assert not line.startswith(("import jax", "from jax")), line
