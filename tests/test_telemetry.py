"""Telemetry: inmem interval aggregation, statsd emission, HTTP surface.

Reference shape: go-metrics wiring at command/agent/command.go:569-605
(inmem sink + SIGUSR1 dump + statsd fanout) and MeasureSince sprinkle
points (consul/fsm.go:121, consul/rpc.go:386)."""

import socket
import time

import pytest

from consul_tpu.utils.telemetry import InmemSink, Metrics


class TestInmemSink:
    def test_counter_aggregates_within_interval(self):
        s = InmemSink(interval_s=10.0)
        now = 1000.0
        s.incr_counter("consul.raft.apply", 1, now)
        s.incr_counter("consul.raft.apply", 1, now + 1)
        s.incr_counter("consul.raft.apply", 3, now + 2)
        snap = s.snapshot()
        assert len(snap) == 1
        c = snap[0]["Counters"]["consul.raft.apply"]
        assert c["count"] == 3 and c["sum"] == 5

    def test_intervals_roll_and_retain(self):
        s = InmemSink(interval_s=10.0, retain=3)
        for i in range(6):
            s.incr_counter("x", 1, 1000.0 + i * 10)
        snap = s.snapshot()
        assert len(snap) == 3  # only the newest `retain` kept
        assert snap[-1]["Interval"] == 1050.0

    def test_sample_min_max_mean(self):
        s = InmemSink()
        now = time.time()
        for v in (2.0, 8.0, 5.0):
            s.add_sample("consul.fsm.kvs", v, now)
        w = s.snapshot()[-1]["Samples"]["consul.fsm.kvs"]
        assert w["min"] == 2.0 and w["max"] == 8.0 and w["mean"] == 5.0

    def test_gauge_last_write_wins(self):
        s = InmemSink()
        now = time.time()
        s.set_gauge("consul.session_ttl.active", 3, now)
        s.set_gauge("consul.session_ttl.active", 7, now)
        assert s.snapshot()[-1]["Gauges"]["consul.session_ttl.active"] == 7

    def test_dump_format(self):
        s = InmemSink()
        now = time.time()
        s.incr_counter("c1", 2, now)
        s.set_gauge("g1", 1.5, now)
        s.add_sample("s1", 4.0, now)
        text = s.dump()
        assert "[C] 'c1': count=1 sum=2.000" in text
        assert "[G] 'g1': 1.500" in text
        assert "[S] 's1':" in text


class TestMetricsRegistry:
    def test_hostname_interposed(self):
        m = Metrics()
        m.configure(hostname="node9")
        m.incr_counter(("consul", "raft", "apply"))
        snap = m.snapshot()
        assert "consul.node9.raft.apply" in snap[-1]["Counters"]

    def test_hostname_disabled(self):
        m = Metrics()
        m.configure(hostname="node9", disable_hostname=True)
        m.incr_counter(("consul", "raft", "apply"))
        assert "consul.raft.apply" in m.snapshot()[-1]["Counters"]

    def test_measure_since_records_ms(self):
        m = Metrics()
        t0 = time.monotonic() - 0.05  # pretend 50ms elapsed
        m.measure_since(("op",), t0)
        w = m.snapshot()[-1]["Samples"]["op"]
        assert 40.0 <= w["mean"] <= 500.0

    def test_statsd_sink_emits_udp_lines(self):
        rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        rx.bind(("127.0.0.1", 0))
        rx.settimeout(5)
        port = rx.getsockname()[1]
        m = Metrics()
        m.configure(statsd_addr=f"127.0.0.1:{port}")
        m.incr_counter(("consul", "rpc", "query"), 2)
        m.set_gauge(("consul", "sessions"), 4.5)
        m.add_sample(("consul", "fsm", "kvs"), 1.25)
        lines = set()
        for _ in range(3):
            lines.add(rx.recvfrom(1024)[0].decode())
        rx.close()
        assert "consul.rpc.query:2|c" in lines
        assert "consul.sessions:4.5|g" in lines
        assert "consul.fsm.kvs:1.25|ms" in lines

    def test_reconfigure_closes_old_sink_and_swaps(self):
        """A reload (SIGHUP path) re-runs configure(): the previous UDP
        socket must be closed — not leaked — and datagrams flow to the
        NEW address only."""
        rx_old = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        rx_old.bind(("127.0.0.1", 0))
        rx_old.settimeout(0.5)
        rx_new = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        rx_new.bind(("127.0.0.1", 0))
        rx_new.settimeout(5)
        m = Metrics()
        m.configure(statsd_addr=f"127.0.0.1:{rx_old.getsockname()[1]}")
        old_sink = m._sinks[1]
        m.configure(statsd_addr=f"127.0.0.1:{rx_new.getsockname()[1]}")
        assert old_sink._sock.fileno() == -1  # closed, not leaked
        m.incr_counter(("consul", "rpc", "query"))
        assert rx_new.recvfrom(1024)[0] == b"consul.rpc.query:1|c"
        with pytest.raises(socket.timeout):
            rx_old.recvfrom(1024)
        rx_old.close()
        rx_new.close()

    def test_statsd_malformed_addr_does_not_raise(self):
        """Bad telemetry config must never take the agent down: a
        malformed port falls back to the statsd default (8125) and
        sends stay fire-and-forget."""
        m = Metrics()
        m.configure(statsd_addr="127.0.0.1:not-a-port")
        assert m._sinks[1]._addr == ("127.0.0.1", 8125)
        m.incr_counter(("consul", "rpc", "query"))  # no exception


class TestAgentIntegration:
    def test_hot_paths_emit_and_http_serves_snapshot(self):
        """Drive KV writes + a DNS query through a live agent, then read
        /v1/agent/metrics and see fsm/raft/http/dns series populated."""

        import httpx

        from test_agent_http import AgentHarness, dns_query

        h = AgentHarness().start()
        try:
            base = h.http_addr
            with httpx.Client(base_url=base, timeout=10) as c:
                for i in range(3):
                    assert c.put(f"/v1/kv/tm{i}", content=b"v").json() is True
                c.put("/v1/catalog/register",
                      json={"Node": "tmnode", "Address": "10.0.0.9"})
                dns_query(h.dns_addr, "tmnode.node.consul")
                snap = c.get("/v1/agent/metrics").json()
            merged_counters = {}
            merged_samples = {}
            for iv in snap:
                merged_counters.update(iv["Counters"])
                merged_samples.update(iv["Samples"])
            assert any(k.endswith("raft.apply") for k in merged_counters), \
                merged_counters
            assert any(".fsm.kvs" in k for k in merged_samples), merged_samples
            assert any(".http." in k for k in merged_samples)
            assert any(".dns.domain_query" in k for k in merged_samples)
        finally:
            h.stop()
