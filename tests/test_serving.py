"""Serving-plane fast path: compact serialization, hot-op parity,
request stats, and the multi-worker front.

The fast path (agent/hotpath.py) computes hot responses as raw bytes
once; these tests pin the properties that keep it honest:

  * wire parity — a hot-path KV GET is byte-identical to the generic
    path's compact JSON (same key order, same b64, same headers);
  * ``?pretty`` still pretty-prints, everything else is compact;
  * 404s and ``?raw`` keep their index headers / octet-stream shape;
  * per-endpoint request stats (obs/reqstats.py) surface in the
    Prometheus exposition and pass tools/check_prom.py;
  * the worker front's hot-subset tables stay in lockstep with the
    edge's (a drifted table silently reroutes traffic);
  * a forked multi-worker agent answers hot and non-hot (proxied)
    routes correctly end to end.
"""

from __future__ import annotations

import asyncio
import json

import httpx
import pytest

from test_agent_http import AgentHarness


@pytest.fixture(scope="module")
def harness():
    h = AgentHarness().start()
    yield h
    h.stop()


def _call(h, coro):
    return asyncio.run_coroutine_threadsafe(coro, h.loop).result(10)


class TestCompactJSON:
    def test_default_compact_pretty_opt_in(self, harness):
        with httpx.Client(base_url=harness.http_addr, timeout=10) as c:
            assert c.put("/v1/kv/compact", content=b"v").json() is True
            flat = c.get("/v1/kv/compact").text
            assert ": " not in flat and ", " not in flat
            pretty = c.get("/v1/kv/compact?pretty").text
            assert pretty.startswith("[\n")
            assert json.loads(flat) == json.loads(pretty)

    def test_hot_get_parity_with_generic_path(self, harness):
        """Same key, hot path (bare GET) vs generic path (?keys-free
        query outside the hot subset forces the generic handler):
        byte-identical body, same index headers."""
        with httpx.Client(base_url=harness.http_addr, timeout=10) as c:
            c.put("/v1/kv/parity?flags=7", content=b"payload")
            hot = c.get("/v1/kv/parity")
            # dc= falls outside _HOT_GET -> generic QueryOptions path.
            generic = c.get("/v1/kv/parity?dc=")
            assert hot.content == generic.content
            assert hot.headers["content-type"] == \
                generic.headers["content-type"]
            for hdr in ("x-consul-index", "x-consul-knownleader"):
                assert hot.headers[hdr] == generic.headers[hdr]
            ent = hot.json()[0]
            assert list(ent.keys()) == [
                "Key", "Value", "Flags", "Session", "LockIndex",
                "CreateIndex", "ModifyIndex"]
            assert ent["Flags"] == 7

    def test_hot_404_keeps_index_headers(self, harness):
        with httpx.Client(base_url=harness.http_addr, timeout=10) as c:
            r = c.get("/v1/kv/definitely-missing")
            assert r.status_code == 404
            assert int(r.headers["x-consul-index"]) >= 0
            assert r.headers["x-consul-knownleader"] == "true"

    def test_hot_raw(self, harness):
        with httpx.Client(base_url=harness.http_addr, timeout=10) as c:
            c.put("/v1/kv/rawkey", content=b"\x00binary\xff")
            r = c.get("/v1/kv/rawkey?raw")
            assert r.content == b"\x00binary\xff"
            assert r.headers["content-type"].startswith(
                "application/octet-stream")

    def test_hot_consistent_and_stale(self, harness):
        with httpx.Client(base_url=harness.http_addr, timeout=10) as c:
            c.put("/v1/kv/modes", content=b"m")
            for qs in ("?consistent", "?stale"):
                r = c.get("/v1/kv/modes" + qs)
                assert r.status_code == 200
                assert r.json()[0]["Key"] == "modes"
            # both at once is contradictory -> generic path rejects
            r = c.get("/v1/kv/modes?consistent&stale")
            assert r.status_code == 400

    def test_status_lease_route(self, harness):
        with httpx.Client(base_url=harness.http_addr, timeout=10) as c:
            ls = c.get("/v1/status/lease").json()
            assert ls["is_leader"] is True
            assert ls["valid"] is True  # single node: always anchored
            assert ls["read_index"] >= 0


class TestRequestStats:
    def test_counters_and_quantiles_exposed(self, harness):
        with httpx.Client(base_url=harness.http_addr, timeout=10) as c:
            for _ in range(5):
                c.get("/v1/kv/stats-probe")
            text = c.get("/v1/agent/metrics?format=prometheus").text
        assert '# TYPE consul_http_requests_total counter' in text
        assert 'consul_http_requests_total{endpoint="kvs"}' in text
        assert '# TYPE consul_http_request_ms summary' in text
        assert 'consul_http_request_ms{endpoint="kvs",quantile="0.5"}' in text
        assert 'consul_http_request_ms_count{endpoint="kvs"}' in text

    def test_exposition_passes_check_prom(self, harness, tmp_path):
        import subprocess
        import sys
        with httpx.Client(base_url=harness.http_addr, timeout=10) as c:
            c.put("/v1/kv/cp", content=b"x")
            c.get("/v1/kv/cp")
            text = c.get("/v1/agent/metrics?format=prometheus").text
        p = tmp_path / "metrics.prom"
        p.write_text(text)
        out = subprocess.run(
            [sys.executable, "tools/check_prom.py", str(p),
             "--require", "consul_http_requests_total",
             "--require", "consul_http_request_ms"],
            capture_output=True, text=True, cwd=_repo_root())
        assert out.returncode == 0, out.stdout + out.stderr

    def test_snapshot_shape(self):
        from consul_tpu.obs.reqstats import EndpointStats
        st = EndpointStats(window=8)
        for ms in (1.0, 2.0, 3.0, 100.0):
            st.record("ep", ms)
        snap = st.snapshot()["ep"]
        assert snap["count"] == 4
        assert snap["p50_ms"] == 3.0
        assert snap["p99_ms"] == 100.0
        rows, summaries = st.prom_families()
        assert rows == [({"endpoint": "ep"}, 4.0)]
        assert summaries[0]["quantiles"][0] == (0.5, 3.0)

    def test_window_bounds_memory(self):
        from consul_tpu.obs.reqstats import EndpointStats
        st = EndpointStats(window=16)
        for i in range(1000):
            st.record("ep", float(i))
        snap = st.snapshot()["ep"]
        assert snap["count"] == 1000          # lifetime counter
        assert snap["p50_ms"] >= 984.0        # ring kept only the tail


class TestWorkerFrontTables:
    def test_hot_subsets_match_edge(self):
        """The worker classifies requests with its own copies of the
        hot-key tables; drift silently sends hot traffic down the slow
        proxy (or worse, non-hot down the fast path)."""
        from consul_tpu.agent import workers
        from consul_tpu.agent.http_api import HTTPServer
        assert workers.HOT_GET == HTTPServer._HOT_GET
        assert workers.HOT_PUT == HTTPServer._HOT_PUT
        assert workers.HOT_DELETE == HTTPServer._HOT_DELETE

    def test_hot_ok_rejects_contradiction_and_strangers(self):
        from consul_tpu.agent.workers import HOT_GET, _hot_ok
        assert _hot_ok({}, HOT_GET)
        assert _hot_ok({"stale": ""}, HOT_GET)
        assert not _hot_ok({"stale": "", "consistent": ""}, HOT_GET)
        assert not _hot_ok({"index": "5"}, HOT_GET)  # blocking -> proxy

    def test_gateway_ops_cover_worker_routes(self):
        from consul_tpu.agent import hotpath
        for op in ("kv_get", "kv_put", "kv_delete", "health_service",
                   "catalog_nodes", "catalog_services", "catalog_service",
                   "status_leader", "status_lease"):
            assert op in hotpath.OPS

    def test_handle_maps_unknown_op(self):
        from consul_tpu.agent import hotpath
        status, _, _, body = asyncio.new_event_loop().run_until_complete(
            hotpath.handle(None, "nope", {}))
        assert status == 500 and b"unknown hot op" in body


@pytest.mark.slow
class TestMultiWorkerBlackbox:
    def test_workers_serve_hot_and_proxied_routes(self):
        """Forked agent with http_workers=3: hot KV round-trips, the
        proxied (?pretty) leg, and gateway-recorded request stats all
        work; shutdown reaps every worker by tracked PID."""
        import sys
        import urllib.request
        sys.path.insert(0, _repo_root() + "/tests")
        from test_blackbox import TestServer
        s = TestServer("mworkers", config_extra={"http_workers": 3})
        try:
            s.start()
            s.wait_for_api()
            s.wait_for_leader()
            base = f"http://127.0.0.1:{s.ports['http']}"

            def req(method, path, data=None):
                r = urllib.request.Request(base + path, data=data,
                                           method=method)
                with urllib.request.urlopen(r, timeout=10) as resp:
                    return resp.status, resp.read()
            import time as _t
            _t.sleep(1.5)  # let the workers bind before driving load
            assert req("PUT", "/v1/kv/mw", b"val")[1] == b"true"
            for _ in range(40):
                st, body = req("GET", "/v1/kv/mw")
                assert st == 200
                assert json.loads(body)[0]["Key"] == "mw"
            st, body = req("GET", "/v1/kv/mw?pretty")  # proxied leg
            assert st == 200 and body.startswith(b"[\n")
            st, body = req("GET", "/v1/agent/metrics?format=prometheus")
            text = body.decode()
            served = {
                name: _scrape_counter(text, name)
                for name in ("kv_get", "kvs")}
            # SO_REUSEPORT spreads connections across master + workers;
            # both planes must have served some share.
            assert served["kv_get"] + served["kvs"] >= 40
            assert served["kv_get"] > 0, \
                "no request reached a worker's gateway"
        finally:
            s.stop()


def _scrape_counter(text: str, endpoint: str) -> float:
    for line in text.splitlines():
        if line.startswith(
                f'consul_http_requests_total{{endpoint="{endpoint}"}}'):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def _repo_root() -> str:
    import os
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
