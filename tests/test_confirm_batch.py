"""ReadIndex confirmation batching under cancellation.

Regression tier for the ADVICE r5 high finding: ``b["fut"]`` is SHARED
by every reader that joined a confirmation batch, so a reader cancelled
mid-batch (client disconnect, request timeout) must not cancel the
batch future out from under its batchmates, and a cancelled/failed
PREVIOUS batch must not unwind the next batch's runner before it fires
(stranding joiners that will never be woken).
"""

import asyncio

import pytest

from consul_tpu.server.server import NotLeaderError, Server


@pytest.fixture()
def loop():
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    yield loop
    loop.close()


def _bare_server() -> Server:
    """Just the batching state — no raft, no pool, no store."""
    srv = object.__new__(Server)
    srv._confirm_batches = {}
    srv._confirm_prev = {}
    srv._confirm_tasks = set()
    return srv


class TestConfirmBatch:
    def test_cancelled_waiter_does_not_poison_batchmates(self, loop):
        async def body():
            srv = _bare_server()
            release = asyncio.Event()
            runs = 0

            async def runner():
                nonlocal runs
                runs += 1
                await release.wait()
                return 42

            waiters = [asyncio.ensure_future(
                srv._confirm_batched("follower_ri", runner))
                for _ in range(3)]
            await asyncio.sleep(0.01)  # all three join the same batch
            waiters[1].cancel()
            await asyncio.sleep(0.01)
            release.set()
            r0 = await waiters[0]
            r2 = await waiters[2]
            assert (r0, r2) == (42, 42)
            with pytest.raises(asyncio.CancelledError):
                await waiters[1]
            assert runs == 1  # one runner for the whole batch

        loop.run_until_complete(body())

    def test_all_waiters_cancelled_still_resolves_future(self, loop):
        """Even with every joiner gone, the batch future must complete
        (the NEXT batch serializes on it via _confirm_prev)."""
        async def body():
            srv = _bare_server()

            async def runner():
                await asyncio.sleep(0.02)
                return 7

            w = asyncio.ensure_future(
                srv._confirm_batched("leader_ri", runner))
            await asyncio.sleep(0.005)
            w.cancel()
            with pytest.raises(asyncio.CancelledError):
                await w
            b = srv._confirm_batches["leader_ri"]
            await asyncio.wait_for(asyncio.shield(b["fut"]), 2.0)
            assert b["fut"].result() == 7

        loop.run_until_complete(body())

    def test_cancelled_prev_batch_does_not_strand_next(self, loop):
        """A cancelled previous batch future must not unwind the next
        runner before it fires — its joiners would wait forever."""
        async def body():
            srv = _bare_server()
            cancelled_prev = asyncio.get_event_loop().create_future()
            cancelled_prev.cancel()
            srv._confirm_prev["follower_ri"] = cancelled_prev

            async def runner():
                return 11

            result = await asyncio.wait_for(
                srv._confirm_batched("follower_ri", runner), 2.0)
            assert result == 11

        loop.run_until_complete(body())

    def test_failed_prev_batch_does_not_strand_next(self, loop):
        async def body():
            srv = _bare_server()
            failed_prev = asyncio.get_event_loop().create_future()
            failed_prev.set_exception(RuntimeError("prior batch died"))
            srv._confirm_prev["leader_ri"] = failed_prev

            async def runner():
                return 13

            assert await asyncio.wait_for(
                srv._confirm_batched("leader_ri", runner), 2.0) == 13
            # retrieve the planted exception: the batcher must skip a
            # failed prev without consuming its error (and an
            # unretrieved future exception fails the vet-dyn harness)
            assert isinstance(failed_prev.exception(), RuntimeError)

        loop.run_until_complete(body())

    def test_not_leader_mapping_preserved(self, loop):
        """The wire contract survives the BaseException hardening: a
        stringified remote not-leader rejection still surfaces as
        NotLeaderError to every joiner."""
        from consul_tpu.rpc.pool import RPCError

        async def body():
            srv = _bare_server()

            async def runner():
                raise RPCError("rpc error: not the leader")

            with pytest.raises(NotLeaderError):
                await asyncio.wait_for(
                    srv._confirm_batched("follower_ri", runner), 2.0)

        loop.run_until_complete(body())

    def test_second_batch_forms_after_fire(self, loop):
        """Joiners arriving after the batch fired get a FRESH batch
        (the linearizability hinge), serialized behind the first."""
        async def body():
            srv = _bare_server()
            order = []
            gate1 = asyncio.Event()

            async def runner1():
                order.append("r1-start")
                await gate1.wait()
                order.append("r1-done")
                return 1

            async def runner2():
                order.append("r2-start")
                return 2

            w1 = asyncio.ensure_future(
                srv._confirm_batched("follower_ri", runner1))
            await asyncio.sleep(0.01)  # batch 1 fired (runner started)
            w2 = asyncio.ensure_future(
                srv._confirm_batched("follower_ri", runner2))
            await asyncio.sleep(0.01)
            # batch 2 must wait for batch 1 to complete
            assert order == ["r1-start"]
            gate1.set()
            assert await w1 == 1
            assert await w2 == 2
            assert order == ["r1-start", "r1-done", "r2-start"]

        loop.run_until_complete(body())
