"""Config loader tests (reference tier: command/agent/config_test.go)."""

import json

import pytest

from consul_tpu.agent.config import (
    Config, ConfigError, decode_config, merge_config, read_config_paths,
    to_agent_config, validate_config)


class TestDecode:
    def test_basic_fields(self):
        cfg = decode_config(json.dumps({
            "node_name": "n1", "datacenter": "dc2", "server": True,
            "bootstrap": True, "data_dir": "/tmp/x",
            "acl_ttl": "45s",
        }))
        assert cfg.node_name == "n1" and cfg.datacenter == "dc2"
        assert cfg.server and cfg.bootstrap
        assert cfg.acl_ttl == 45.0

    def test_ports_block(self):
        cfg = decode_config('{"ports": {"dns": 9600, "http": 9500}}')
        assert cfg.ports.dns == 9600 and cfg.ports.http == 9500
        assert cfg.ports.serf_lan == 8301  # default preserved

    def test_dns_config(self):
        cfg = decode_config(json.dumps({
            "dns_config": {"node_ttl": "10s", "only_passing": True,
                           "service_ttl": {"*": "5s", "web": "30s"}}}))
        assert cfg.dns_config.node_ttl == 10.0
        assert cfg.dns_config.only_passing
        assert cfg.dns_config.service_ttl == {"*": 5.0, "web": 30.0}

    def test_unknown_key_rejected(self):
        with pytest.raises(ConfigError):
            decode_config('{"bogus_key": 1}')
        with pytest.raises(ConfigError):
            decode_config('{"ports": {"bogus": 1}}')

    def test_service_stanza_singular(self):
        cfg = decode_config(json.dumps({
            "service": {"name": "web", "port": 80,
                        "check": {"script": "true", "interval": "10s"}}}))
        assert len(cfg.services) == 1
        assert cfg.services[0]["name"] == "web"

    def test_invalid_json(self):
        with pytest.raises(ConfigError):
            decode_config("{nope")


class TestMerge:
    def test_overlay_and_append(self):
        a = decode_config('{"node_name": "a", "datacenter": "dc1", '
                          '"service": {"name": "s1"}}')
        b = decode_config('{"node_name": "b", "service": {"name": "s2"}}')
        m = merge_config(a, b)
        assert m.node_name == "b"           # b wins
        assert m.datacenter == "dc1"        # a preserved
        assert [s["name"] for s in m.services] == ["s1", "s2"]  # appended

    def test_unset_fields_do_not_clobber(self):
        a = decode_config('{"server": true}')
        b = decode_config('{"node_name": "x"}')
        m = merge_config(a, b)
        assert m.server is True

    def test_nested_blocks_merge_fieldwise(self):
        a = decode_config('{"ports": {"dns": 5600}}')
        b = decode_config('{"ports": {"http": 9500}}')
        m = merge_config(a, b)
        assert m.ports.dns == 5600     # earlier override survives
        assert m.ports.http == 9500
        assert m.ports.serf_lan == 8301
        a = decode_config('{"dns_config": {"only_passing": true}}')
        b = decode_config('{"dns_config": {"node_ttl": "10s"}}')
        m = merge_config(a, b)
        assert m.dns_config.only_passing and m.dns_config.node_ttl == 10.0


class TestReadPaths:
    def test_dir_lexical_order(self, tmp_path):
        d = tmp_path / "conf.d"
        d.mkdir()
        (d / "10-base.json").write_text('{"node_name": "early"}')
        (d / "20-override.json").write_text('{"node_name": "late"}')
        (d / "ignored.txt").write_text("not json")
        cfg = read_config_paths([str(d)])
        assert cfg.node_name == "late"

    def test_file_then_dir(self, tmp_path):
        f = tmp_path / "base.json"
        f.write_text('{"datacenter": "dc9", "server": true}')
        d = tmp_path / "conf.d"
        d.mkdir()
        (d / "x.json").write_text('{"node_name": "n"}')
        cfg = read_config_paths([str(f), str(d)])
        assert cfg.datacenter == "dc9" and cfg.node_name == "n"

    def test_error_names_file(self, tmp_path):
        f = tmp_path / "bad.json"
        f.write_text("{broken")
        with pytest.raises(ConfigError) as ei:
            read_config_paths([str(f)])
        assert "bad.json" in str(ei.value)


class TestValidate:
    def test_bootstrap_needs_server(self):
        cfg = decode_config('{"bootstrap": true}')
        assert any("server mode" in p for p in validate_config(cfg))

    def test_bootstrap_expect_conflicts(self):
        cfg = decode_config('{"server": true, "bootstrap": true, '
                            '"bootstrap_expect": 3}')
        assert any("bootstrap-expect" in p for p in validate_config(cfg))

    def test_bad_encrypt_key(self):
        cfg = decode_config('{"encrypt": "tooshort"}')
        assert any("16 bytes" in p or "base64" in p
                   for p in validate_config(cfg))

    def test_good_encrypt_key(self):
        import base64, os
        key = base64.b64encode(os.urandom(16)).decode()
        cfg = decode_config(json.dumps({"encrypt": key}))
        assert validate_config(cfg) == []

    def test_bad_watch(self):
        cfg = decode_config('{"watches": [{"type": "bogus"}]}')
        assert any("watch" in p.lower() for p in validate_config(cfg))

    def test_verify_incoming_needs_certs(self):
        cfg = decode_config('{"verify_incoming": true}')
        assert any("ca_file" in p for p in validate_config(cfg))


class TestAdapter:
    def test_to_agent_config(self):
        cfg = decode_config(json.dumps({
            "node_name": "n1", "server": True, "bootstrap": True,
            "ports": {"http": 9500, "dns": 9600},
            "acl_datacenter": "dc1", "acl_token": "tok",
            "dns_config": {"only_passing": True}}))
        a = to_agent_config(cfg)
        assert a.node_name == "n1" and a.http_port == 9500
        assert a.dns_port == 9600 and a.dns_only_passing
        assert a.acl_datacenter == "dc1" and a.acl_token == "tok"
