"""CLI + IPC tests.

Tier 4 of SURVEY.md §4: black-box tests that fork/exec the real CLI
binary (`python -m consul_tpu.cli.main agent ...`) and drive it over
HTTP/IPC — the closest equivalent of testutil.TestServer's forked
consul binary."""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from consul_tpu.ipc import IPCClient, IPCError


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
       "PYTHONPATH": os.path.dirname(os.path.dirname(__file__))}
ENV.pop("PALLAS_AXON_POOL_IPS", None)


def _cli(*args, timeout=30, **kw):
    return subprocess.run(
        [sys.executable, "-m", "consul_tpu.cli.main", *args],
        capture_output=True, text=True, timeout=timeout, env=ENV, **kw)


@pytest.fixture(scope="module")
def daemon(tmp_path_factory):
    """Fork/exec a real agent daemon (testutil/server.go:133-142 shape)."""
    data_dir = tmp_path_factory.mktemp("agent-data")
    http, dns, rpc = _free_port(), _free_port(), _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "consul_tpu.cli.main", "agent",
         "-server", "-bootstrap", "-node", "cli-node",
         "-data-dir", str(data_dir),
         "-http-port", str(http), "-dns-port", str(dns),
         "-rpc-port", str(rpc)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=ENV)
    # wait for the ready banner
    deadline = time.time() + 30
    import httpx
    while time.time() < deadline:
        try:
            r = httpx.get(f"http://127.0.0.1:{http}/v1/status/leader",
                          timeout=1.0)
            if r.status_code == 200 and r.json():
                break
        except Exception:
            pass
        if proc.poll() is not None:
            out = proc.stdout.read()
            raise RuntimeError(f"agent died: {out}")
        time.sleep(0.2)
    else:
        proc.kill()
        raise RuntimeError("agent never became ready")
    yield {"http": f"127.0.0.1:{http}", "rpc": f"127.0.0.1:{rpc}",
           "proc": proc}
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(10)
    except subprocess.TimeoutExpired:
        proc.kill()


class TestCLIBasics:
    def test_version(self):
        r = _cli("version")
        assert r.returncode == 0 and "consul-tpu v" in r.stdout

    def test_keygen(self):
        import base64
        r = _cli("keygen")
        assert r.returncode == 0
        assert len(base64.b64decode(r.stdout.strip())) == 16

    def test_configtest_valid(self, tmp_path):
        f = tmp_path / "good.json"
        f.write_text('{"server": true, "bootstrap": true}')
        r = _cli("configtest", "-config-file", str(f))
        assert r.returncode == 0 and "valid" in r.stdout

    def test_configtest_invalid(self, tmp_path):
        f = tmp_path / "bad.json"
        f.write_text('{"bootstrap": true}')
        r = _cli("configtest", "-config-file", str(f))
        assert r.returncode == 1


class TestAgainstDaemon:
    def test_info(self, daemon):
        r = _cli("info", "-rpc-addr", daemon["rpc"])
        assert r.returncode == 0
        assert "raft:" in r.stdout and "state = Leader" in r.stdout

    def test_members(self, daemon):
        r = _cli("members", "-rpc-addr", daemon["rpc"])
        assert r.returncode == 0 and "cli-node" in r.stdout
        r = _cli("members", "-wan", "-rpc-addr", daemon["rpc"])
        assert "cli-node.dc1" in r.stdout

    def test_event(self, daemon):
        r = _cli("event", "-name", "deploy", "-http-addr", daemon["http"])
        assert r.returncode == 0 and "Event ID:" in r.stdout

    def test_exec(self, daemon):
        r = _cli("exec", "-http-addr", daemon["http"], "-wait", "15",
                 "echo", "cli-exec-output", timeout=60)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "cli-exec-output" in r.stdout
        assert "finished with exit code 0" in r.stdout

    def test_maint(self, daemon):
        r = _cli("maint", "-enable", "-reason", "upgrades",
                 "-http-addr", daemon["http"])
        assert r.returncode == 0
        r = _cli("maint", "-http-addr", daemon["http"])
        assert "upgrades" in r.stdout
        r = _cli("maint", "-disable", "-http-addr", daemon["http"])
        assert r.returncode == 0
        r = _cli("maint", "-http-addr", daemon["http"])
        assert "normal mode" in r.stdout

    def test_lock(self, daemon):
        r = _cli("lock", "-http-addr", daemon["http"],
                 "locktest", "echo locked-$$", timeout=60)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_keyring_not_configured(self, daemon):
        r = _cli("keyring", "-list", "-rpc-addr", daemon["rpc"])
        assert r.returncode == 1
        assert "keyring" in r.stderr.lower()

    def test_reload(self, daemon):
        r = _cli("reload", "-rpc-addr", daemon["rpc"])
        assert r.returncode == 0

    def test_ipc_monitor_streams_logs(self, daemon):
        lines = []
        with IPCClient(daemon["rpc"]) as c:
            seq = c.monitor(lines.append)
            # trigger some log output via a reload
            with IPCClient(daemon["rpc"]) as c2:
                c2.reload()
            deadline = time.time() + 5
            while time.time() < deadline and not any(
                    "reload" in l for l in lines):
                c.pump(timeout=0.5)
            c.stop_monitor(seq)
        assert any("agent: reloading" in l for l in lines), lines

    def test_ipc_handshake_required(self, daemon):
        import msgpack
        host, _, port = daemon["rpc"].rpartition(":")
        s = socket.create_connection((host, int(port)), timeout=5)
        s.sendall(msgpack.packb({"Command": "stats", "Seq": 1}))
        unp = msgpack.Unpacker(raw=False)
        unp.feed(s.recv(4096))
        resp = next(unp)
        assert "Handshake" in resp["Error"]
        s.close()

    def test_ipc_unknown_command(self, daemon):
        with IPCClient(daemon["rpc"]) as c:
            with pytest.raises(IPCError):
                c._call("frobnicate")


class TestWatchCLI:
    def test_watch_via_cli(self, daemon, tmp_path):
        out = tmp_path / "events"
        proc = subprocess.Popen(
            [sys.executable, "-m", "consul_tpu.cli.main", "watch",
             "-http-addr", daemon["http"], "-type", "key",
             "-key", "cliw/x",
             "-handler", f"cat >> {out}"],
            env=ENV, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        try:
            time.sleep(1.0)
            import httpx
            httpx.put(f"http://{daemon['http']}/v1/kv/cliw/x", content=b"v1")
            deadline = time.time() + 10
            while time.time() < deadline:
                if out.exists() and "cliw/x" in out.read_text():
                    break
                time.sleep(0.2)
            assert out.exists() and "cliw/x" in out.read_text()
        finally:
            proc.terminate()
            proc.wait(5)


class TestSyslogSink:
    def test_syslog_sink_formats_pri_and_strips_stamp(self, tmp_path):
        """RFC3164 datagrams: facility*8+severity PRI, tag prefix, level
        recovered from the hub's line format (syslog.go role).  Served
        by a local AF_UNIX datagram socket standing in for /dev/log."""
        import socket
        from unittest import mock

        from consul_tpu.agent.log import LogHub, syslog_sink

        path = str(tmp_path / "log.sock")
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        srv.bind(path)
        srv.settimeout(5)
        real_connect = socket.socket.connect
        with mock.patch.object(
                socket.socket, "connect",
                lambda self, addr: real_connect(
                    self, path if addr == "/dev/log" else addr)):
            sink = syslog_sink("LOCAL1", tag="test-agent")
        hub = LogHub("INFO")
        hub.add_sink(sink, level="INFO", replay=False)
        hub.warn("disk almost full")
        data = srv.recv(4096).decode()
        srv.close()
        # LOCAL1=17, WARN severity=4 -> PRI 17*8+4 = 140
        assert data.startswith("<140>test-agent: "), data
        assert data.endswith("disk almost full"), data
        assert "[WARN]" not in data  # stamp/level prefix stripped

    def test_syslog_unavailable_raises(self):
        import socket
        from unittest import mock

        from consul_tpu.agent.log import syslog_sink
        with mock.patch.object(socket.socket, "connect",
                               side_effect=OSError("no /dev/log")):
            import pytest as _pytest
            with _pytest.raises(OSError):
                syslog_sink()
