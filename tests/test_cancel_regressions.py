"""Cancellation-safety regressions pinned by the vet Q-tier (Q01-Q04)
and the vet-dyn cancel-injection sweep.

Each test reproduces a real cancellation schedule the tier caught on
this tree and asserts the hand-off contract that was broken:

- a successor confirm-batch runner cancelled while serializing on its
  predecessor must neither cancel the predecessor's shared future
  (``asyncio.shield``) nor strand its own joiners (BaseException
  handler resolves ``b["fut"]`` before re-raising);
- a batch killed before it fired is a tombstone: new requests on the
  key must form a fresh batch, not inherit the canceller's error;
- ``Server.stop()`` must cancel AND await the fire-and-forget runners;
- raft's ``_sync_pump`` is the only resolver of durability waiters, so
  any pump exit — cancellation or an escaped bug — must fail them;
- the gateway read loop is the only resolver of in-flight request
  futures, so any exit must fail them (``request()`` would otherwise
  hang forever on a dead reader).
"""

import asyncio

import msgpack
import pytest

from consul_tpu.agent.workers import GatewayClient
from consul_tpu.consensus.raft import (
    NotLeaderError as RaftNotLeaderError, RaftConfig, RaftNode)
from consul_tpu.server.server import Server, ServerConfig


@pytest.fixture()
def loop():
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    yield loop
    loop.close()


def _bare_server() -> Server:
    """Just the batching state — no raft, no pool, no store."""
    srv = object.__new__(Server)
    srv._confirm_batches = {}
    srv._confirm_prev = {}
    srv._confirm_tasks = set()
    return srv


class TestSuccessorRunnerCancellation:
    """Batch A is in flight; batch B's runner awaits shield(prev)."""

    async def _two_batches(self, srv, gate_a):
        async def runner_a():
            await gate_a.wait()
            return "a"

        async def runner_b():
            return "b"

        a_joiners = [asyncio.ensure_future(
            srv._confirm_batched("ri", runner_a)) for _ in range(2)]
        await asyncio.sleep(0.01)  # batch A fires, parks on gate_a
        before = set(srv._confirm_tasks)
        b_joiners = [asyncio.ensure_future(
            srv._confirm_batched("ri", runner_b)) for _ in range(2)]
        await asyncio.sleep(0.01)  # batch B's runner blocks on prev
        runner_task = next(
            t for t in srv._confirm_tasks if t not in before)
        return a_joiners, b_joiners, runner_task

    def test_cancelled_successor_spares_predecessor(self, loop):
        async def body():
            srv = _bare_server()
            gate_a = asyncio.Event()
            a_joiners, b_joiners, runner_b = (
                await self._two_batches(srv, gate_a))
            prev = srv._confirm_prev["ri"]  # batch A's shared future

            runner_b.cancel()
            await asyncio.sleep(0.01)
            # The shield spared the predecessor: batch A is untouched
            # and its joiners still resolve normally.
            assert not prev.cancelled()
            gate_a.set()
            assert await asyncio.wait_for(a_joiners[0], 2.0) == "a"
            assert await asyncio.wait_for(a_joiners[1], 2.0) == "a"
            # Batch B's joiners were RESOLVED (with the cancellation),
            # never stranded on an unfired batch.
            for w in b_joiners:
                with pytest.raises(asyncio.CancelledError):
                    await asyncio.wait_for(w, 2.0)

        loop.run_until_complete(body())

    def test_dead_unfired_batch_is_a_tombstone(self, loop):
        """A batch killed before it fired keeps ``fired=False`` with a
        resolved future; joining it would hand the canceller's error to
        every future caller on the key, forever."""
        async def body():
            srv = _bare_server()
            gate_a = asyncio.Event()
            a_joiners, b_joiners, runner_b = (
                await self._two_batches(srv, gate_a))
            runner_b.cancel()
            gate_a.set()
            await asyncio.gather(*a_joiners, *b_joiners, runner_b,
                                 return_exceptions=True)
            rec = srv._confirm_batches["ri"]
            assert rec["fut"].done() and not rec["fired"]

            async def fresh():
                return "fresh"

            got = await asyncio.wait_for(
                srv._confirm_batched("ri", fresh), 2.0)
            assert got == "fresh"

        loop.run_until_complete(body())


class TestStopDrainsConfirmRunners:
    def test_stop_cancels_and_awaits_parked_runner(self, loop):
        """A runner parked mid-confirmation when stop() is called must
        be cancelled, awaited, and must resolve its batch future —
        joiners get an exception, never a hang or a destroyed-pending
        task at loop close."""
        async def body():
            srv = Server(ServerConfig(
                node_name="solo",
                raft=RaftConfig(heartbeat_interval=0.02,
                                election_timeout_min=0.1,
                                election_timeout_max=0.2,
                                rpc_timeout=0.05)))
            await srv.start()
            await srv.wait_for_leader()
            parked = asyncio.Event()

            async def runner():
                parked.set()
                await asyncio.Event().wait()  # parks until cancelled

            w = asyncio.ensure_future(
                srv._confirm_batched("leader_ri", runner))
            await asyncio.wait_for(parked.wait(), 2.0)
            await asyncio.wait_for(srv.stop(), 5.0)
            assert not srv._confirm_tasks
            with pytest.raises(asyncio.CancelledError):
                await asyncio.wait_for(w, 2.0)

        loop.run_until_complete(body())


class TestSyncPumpFailsDurabilityWaiters:
    def _node(self) -> RaftNode:
        return RaftNode("n0", ["n0"], fsm=None, transport=None)

    def test_pump_cancellation_fails_waiters(self, loop):
        async def body():
            node = self._node()
            pump = asyncio.ensure_future(node._sync_pump())
            waiter = asyncio.ensure_future(node._wait_durable(5))
            await asyncio.sleep(0.02)
            assert not waiter.done()
            pump.cancel()
            await asyncio.gather(pump, return_exceptions=True)
            with pytest.raises(RaftNotLeaderError):
                await asyncio.wait_for(waiter, 2.0)

        loop.run_until_complete(body())

    def test_pump_escaped_bug_fails_waiters(self, loop):
        """An exception escaping the pump's retry path (only fsync
        errors are retried) must not leave waiters hanging until
        shutdown."""
        async def body():
            node = self._node()

            def boom():
                raise ValueError("log store gone")

            node.log.last_index = boom
            pump = asyncio.ensure_future(node._sync_pump())
            waiter = asyncio.ensure_future(node._wait_durable(5))
            with pytest.raises(RaftNotLeaderError):
                await asyncio.wait_for(waiter, 2.0)
            await asyncio.gather(pump, return_exceptions=True)
            assert isinstance(pump.exception(), ValueError)

        loop.run_until_complete(body())


class TestGatewayReadLoopFailsPending:
    def test_unexpected_reader_error_fails_pending(self, loop):
        """A decode/read error outside the expected connection-loss
        classes must still fail in-flight requests — the read loop is
        their only resolver."""
        async def body():
            gc = GatewayClient("/tmp/does-not-exist.sock")
            fut = asyncio.get_event_loop().create_future()
            gc._pending[7] = fut

            class _Corrupt:
                async def read(self, n):
                    raise ValueError("corrupt frame")

            task = asyncio.ensure_future(
                gc._read_loop(_Corrupt(), msgpack.Unpacker(raw=False)))
            with pytest.raises(ConnectionError):
                await asyncio.wait_for(fut, 2.0)
            await asyncio.gather(task, return_exceptions=True)
            assert not gc._pending

        loop.run_until_complete(body())

    def test_reader_cancellation_fails_pending(self, loop):
        async def body():
            gc = GatewayClient("/tmp/does-not-exist.sock")
            fut = asyncio.get_event_loop().create_future()
            gc._pending[7] = fut

            class _Hang:
                async def read(self, n):
                    await asyncio.Event().wait()

            task = asyncio.ensure_future(
                gc._read_loop(_Hang(), msgpack.Unpacker(raw=False)))
            await asyncio.sleep(0.01)
            task.cancel()
            with pytest.raises(ConnectionError):
                await asyncio.wait_for(fut, 2.0)
            await asyncio.gather(task, return_exceptions=True)
            assert not gc._pending

        loop.run_until_complete(body())
