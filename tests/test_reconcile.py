"""Lockstep equivalence for the fused reconcile write path (PR 18).

The batched reconcile (agent/reconcile.py) folds one drain cadence's
member transitions into a single ``MessageType.BATCH`` raft envelope
(consensus/fsm.py ``_apply_batch_envelope``).  Its correctness claim is
*equivalence*: the envelope applied at index N leaves the store
byte-identical to the same sub-entries applied sequentially at N, fires
the same watch tables, and returns the same per-sub results.  The
through-raft tier then holds a live 3-node cluster to convergence
across a leader change, and the byte-cache tier holds the FSM render
hook's pre-warmed bytes to identity with the cold health path.
"""

from __future__ import annotations

import asyncio

import msgpack
import pytest

from consul_tpu.agent.reconcile import Reconciler, reconstats
from consul_tpu.consensus.fsm import ConsulFSM
from consul_tpu.membership.swim import (
    STATE_ALIVE, STATE_DEAD, STATE_LEFT, Node)
from consul_tpu.structs import codec
from consul_tpu.structs.structs import (
    HEALTH_CRITICAL,
    HEALTH_PASSING,
    DeregisterRequest,
    HealthCheck,
    KVSRequest,
    DirEntry,
    MessageType,
    NodeService,
    QueryOptions,
    RegisterRequest,
    SERF_CHECK_ID,
    SERF_CHECK_NAME,
)

# -- helpers ---------------------------------------------------------------


def enc(msg_type, req) -> bytes:
    return codec.encode(int(msg_type), req)


def envelope(ops) -> bytes:
    """Exactly server.raft_apply_batch's encoding."""
    subs = [enc(t, r) for t, r in ops]
    return bytes([int(MessageType.BATCH)]) + msgpack.packb(
        subs, use_bin_type=True)


def serf_register(name: str, addr: str, status: str,
                  service: NodeService = None) -> RegisterRequest:
    req = RegisterRequest(
        node=name, address=addr, service=service,
        check=HealthCheck(node=name, check_id=SERF_CHECK_ID,
                          name=SERF_CHECK_NAME, status=status))
    # Same normalization the batched submit applies (check -> checks).
    req.checks.append(req.check)
    req.check = None
    return req


class RecordingWaiter:
    def __init__(self) -> None:
        self.fired = False

    def set(self) -> None:
        self.fired = True


def fired_tables(store, fn):
    """Run ``fn`` with a waiter parked on every catalog table; return
    the set of tables whose NotifyGroup fired."""
    tables = ("nodes", "services", "checks")
    waiters = {t: RecordingWaiter() for t in tables}
    for t, w in waiters.items():
        store.watch([t], w)
    fn()
    for t, w in waiters.items():
        store.stop_watch([t], w)
    return {t for t, w in waiters.items() if w.fired}


def assert_lockstep(seed_ops, batch_ops, index=40):
    """Envelope at ``index`` == the same subs applied sequentially at
    ``index``: byte-identical snapshot, same fired watch tables, same
    per-sub results."""
    fsm_seq, fsm_bat = ConsulFSM(), ConsulFSM()
    for fsm in (fsm_seq, fsm_bat):
        for i, (t, req) in enumerate(seed_ops):
            fsm.apply(10 + i, enc(t, req))

    seq_results = []

    def run_seq():
        for t, req in batch_ops:
            try:
                seq_results.append(fsm_seq.apply(index, enc(t, req)))
            except Exception as exc:
                seq_results.append(f"{type(exc).__name__}: {exc}")

    seq_fired = fired_tables(fsm_seq.store, run_seq)
    bat_results = []
    bat_fired = fired_tables(
        fsm_bat.store,
        lambda: bat_results.extend(
            fsm_bat.apply(index, envelope(batch_ops))))

    assert bat_results == seq_results
    assert bat_fired == seq_fired
    assert fsm_bat.snapshot(index) == fsm_seq.snapshot(index)
    return fsm_bat


# -- envelope lockstep -----------------------------------------------------


class TestEnvelopeLockstep:
    def test_healthy_join_burst(self):
        ops = [(MessageType.REGISTER,
                serf_register(f"n{i}", f"10.0.0.{i + 1}", HEALTH_PASSING))
               for i in range(8)]
        fsm = assert_lockstep([], ops)
        assert len(fsm.store.nodes()[1]) == 8

    def test_churn_mixed_batch(self):
        seed = [(MessageType.REGISTER,
                 serf_register(f"n{i}", f"10.0.0.{i + 1}", HEALTH_PASSING,
                               service=NodeService(id="web", service="web",
                                                   port=80)))
                for i in range(3)]
        ops = [
            (MessageType.REGISTER,
             serf_register("n9", "10.0.0.99", HEALTH_PASSING)),
            (MessageType.REGISTER,
             serf_register("n0", "10.0.0.1", HEALTH_CRITICAL)),
            (MessageType.DEREGISTER, DeregisterRequest(node="n2")),
        ]
        fsm = assert_lockstep(seed, ops)
        _, checks = fsm.store.node_checks("n0")
        assert any(c.check_id == SERF_CHECK_ID
                   and c.status == HEALTH_CRITICAL for c in checks)
        assert fsm.store.get_node("n2")[1] is None

    def test_refute_after_detect_same_batch(self):
        """Detect + refute for the same member inside one cadence: the
        envelope applies both in arrival order, landing on the refuted
        (passing) verdict exactly like the sequential loop."""
        seed = [(MessageType.REGISTER,
                 serf_register("n0", "10.0.0.1", HEALTH_PASSING))]
        ops = [
            (MessageType.REGISTER,
             serf_register("n0", "10.0.0.1", HEALTH_CRITICAL)),
            (MessageType.REGISTER,
             serf_register("n0", "10.0.0.1", HEALTH_PASSING)),
        ]
        fsm = assert_lockstep(seed, ops)
        _, checks = fsm.store.node_checks("n0")
        assert [c.status for c in checks
                if c.check_id == SERF_CHECK_ID] == [HEALTH_PASSING]

    def test_failed_sub_is_isolated(self):
        """A sub that raises yields a wire-safe error string in its
        result slot; the other subs still apply (N independent
        sequential entries would behave the same)."""
        bad = KVSRequest(op=99, dir_ent=DirEntry(key="k"))
        ops = [
            (MessageType.REGISTER,
             serf_register("n0", "10.0.0.1", HEALTH_PASSING)),
            (MessageType.KVS, bad),
            (MessageType.REGISTER,
             serf_register("n1", "10.0.0.2", HEALTH_PASSING)),
        ]
        fsm = assert_lockstep([], ops)
        results = fsm.apply(41, envelope(ops))
        assert results[0] is None and results[2] is None
        assert isinstance(results[1], str) and "ValueError" in results[1]
        assert fsm.store.get_node("n1")[1] == "10.0.0.2"


# -- reconciler coalescing (op builders against a stub server) -------------


class _StubRaft:
    def __init__(self):
        self.peers = set()

    async def add_peer(self, name):
        self.peers.add(name)

    async def remove_peer(self, name):
        self.peers.discard(name)


class _StubConfig:
    node_name = "leader0"
    datacenter = "dc1"


class _StubServer:
    """Just enough server for Reconciler: a real FSM behind
    raft_apply_batch, applying each envelope at the next index."""

    def __init__(self):
        self.fsm = ConsulFSM()
        self.store = self.fsm.store
        self.raft = _StubRaft()
        self.config = _StubConfig()
        self.index = 100
        self.batches = []

    async def raft_apply_batch(self, ops):
        self.batches.append(list(ops))
        self.index += 1
        return self.fsm.apply(self.index, envelope(ops))


class TestReconcilerCoalesce:
    def test_latest_wins_refute_after_detect(self):
        async def main():
            srv = _StubServer()
            rec = Reconciler(srv)
            merged0 = reconstats.events_merged
            rec.note(Node(name="n0", addr="10.0.0.1", port=8301,
                          state=STATE_DEAD))
            rec.note(Node(name="n0", addr="10.0.0.1", port=8301,
                          state=STATE_ALIVE))
            assert len(rec) == 1
            assert reconstats.events_merged == merged0 + 1
            assert await rec.flush() == 1
            assert len(srv.batches) == 1 and len(srv.batches[0]) == 1
            _, checks = srv.store.node_checks("n0")
            assert [c.status for c in checks
                    if c.check_id == SERF_CHECK_ID] == [HEALTH_PASSING]
        asyncio.run(main())

    def test_store_compare_skips_clean_members(self):
        async def main():
            srv = _StubServer()
            rec = Reconciler(srv)
            rec.note(Node(name="n0", addr="10.0.0.1", port=8301,
                          state=STATE_ALIVE))
            assert await rec.flush() == 1
            # Same member, same state, same addr: every op builder's
            # store compare skips — nothing submitted.
            rec.note(Node(name="n0", addr="10.0.0.1", port=8301,
                          state=STATE_ALIVE))
            assert await rec.flush() == 0
            assert len(srv.batches) == 1
        asyncio.run(main())

    def test_left_member_deregisters(self):
        async def main():
            srv = _StubServer()
            rec = Reconciler(srv)
            rec.note(Node(name="n0", addr="10.0.0.1", port=8301,
                          state=STATE_ALIVE))
            await rec.flush()
            rec.note(Node(name="n0", addr="10.0.0.1", port=8301,
                          state=STATE_LEFT))
            assert await rec.flush() == 1
            assert srv.store.get_node("n0")[1] is None
        asyncio.run(main())

    def test_submit_failure_drops_pending(self):
        async def main():
            srv = _StubServer()

            async def boom(ops):
                raise RuntimeError("lost leadership")

            srv.raft_apply_batch = boom
            rec = Reconciler(srv)
            fail0 = reconstats.submit_failures
            rec.note(Node(name="n0", addr="10.0.0.1", port=8301,
                          state=STATE_ALIVE))
            assert await rec.flush() == 0
            assert reconstats.submit_failures == fail0 + 1
            # Pending was consumed, not retried: the periodic full
            # reconcile owns the repair, same as the sequential loop.
            assert len(rec) == 0
        asyncio.run(main())


# -- through-raft convergence (live cluster) -------------------------------

from tests.test_server_cluster import (  # noqa: E402
    make_servers, start_and_elect, stop_all, wait_until)


def _serf_status(srv, name):
    _, checks = srv.store.node_checks(name)
    for c in checks:
        if c.check_id == SERF_CHECK_ID:
            return c.status
    return None


def test_batched_reconcile_converges_across_leader_change():
    """Members injected into the batched reconcile land identically on
    every server, and a leader change mid-stream hands the stream to
    the new leader's reconciler without losing members."""
    async def main():
        _, servers = make_servers(3)
        leader = await start_and_elect(servers)
        first = [f"g{i}" for i in range(6)]
        for i, g in enumerate(first):
            leader.membership_notify("member-join", Node(
                name=g, addr=f"10.9.0.{i + 1}", port=8301,
                state=STATE_ALIVE))
        await wait_until(
            lambda: all(_serf_status(s, g) == HEALTH_PASSING
                        for s in servers for g in first),
            msg="first batch replicated everywhere")

        # Depose the leader; the stream continues on its successor.
        await leader.stop()
        rest = [s for s in servers if s is not leader]
        await wait_until(
            lambda: any(s.is_leader() for s in rest), msg="re-election")
        leader2 = next(s for s in rest if s.is_leader())
        for i, g in enumerate(first):
            leader2.membership_notify("member-failed", Node(
                name=g, addr=f"10.9.0.{i + 1}", port=8301,
                state=STATE_DEAD))
        await wait_until(
            lambda: all(_serf_status(s, g) == HEALTH_CRITICAL
                        for s in rest for g in first),
            msg="post-failover batch replicated")
        # Byte-identical stores on the survivors.
        assert rest[0].fsm.snapshot(0) == rest[1].fsm.snapshot(0)
        await stop_all(rest)
    asyncio.run(main())


def test_health_cache_byte_parity_with_cold_path():
    """The FSM batch-boundary render hook pre-warms bytes that are
    IDENTICAL to the cold Health.service_nodes pipeline, and the next
    lookup serves them without re-rendering."""
    async def main():
        from consul_tpu.agent.hotpath import _dumps, attach_health_cache
        from consul_tpu.agent.http_api import to_api

        _, servers = make_servers(1)
        leader = await start_and_elect(servers)
        cache = attach_health_cache(leader)
        await leader.catalog.register(RegisterRequest(
            node="web1", address="10.9.1.1",
            service=NodeService(id="web", service="web", port=80),
            check=HealthCheck(node="web1", check_id=SERF_CHECK_ID,
                              name=SERF_CHECK_NAME,
                              status=HEALTH_PASSING)))
        # Populate the cached variant, then flip the node through the
        # batched reconcile: the hook must re-render it at the batch
        # boundary.
        cache.render("web", "", False)
        leader.membership_notify("member-failed", Node(
            name="web1", addr="10.9.1.1", port=8301, state=STATE_DEAD))
        await wait_until(
            lambda: _serf_status(leader, "web1") == HEALTH_CRITICAL,
            msg="failed transition applied")

        hits0 = cache.hits
        row = cache.lookup(("web", "", False))
        assert row is not None, "hook-rendered bytes were not index-valid"
        assert cache.hits == hits0 + 1
        _vidx, status, _ctype, body, hidx = row
        meta, csns = await leader.health.service_nodes(
            "web", QueryOptions(), "", False)
        assert status == 200
        assert body == _dumps(to_api(csns))
        assert hidx == meta.index
        # The hot bytes carry the fused verdict, not the stale one.
        assert HEALTH_CRITICAL.encode() in body
        await stop_all(servers)
    asyncio.run(main())


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
