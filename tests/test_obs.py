"""Observability surfaces: tracer, flight recorder, Prometheus render.

Covers the PR-1 tentpole units in-process:

- span nesting / ring promotion / cross-process take+ingest stitching
  (obs/trace.py),
- FlightRecorder drain semantics: cursor deltas, ring wraparound,
  overflow accounting, registry folding (obs/flight.py),
- the kernel-side flight ring: enabling it must NOT change the SWIM
  round dynamics (bit-identical state) and must record sensible
  per-round rows with zero host transfers inside the scan
  (gossip/kernel.py),
- Prometheus text exposition over a registry carrying telemetry AND
  flight series, parsed by a strict line validator (obs/prom.py).
"""

import re

import pytest

from consul_tpu.obs import trace as obs_trace
from consul_tpu.obs.flight import (
    FLIGHT_COLS, N_COLS, FlightRecorder)
from consul_tpu.obs.prom import render_prometheus, sanitize
from consul_tpu.obs.trace import (
    RING_TRACES, SpanContext, Tracer, child_span, current_context,
    finish_span, root_span, server_span)
from consul_tpu.utils.telemetry import Metrics


@pytest.fixture(autouse=True)
def _clean_tracer():
    obs_trace.tracer.clear()
    yield
    obs_trace.tracer.clear()


class TestTracer:
    def test_root_child_nesting(self):
        root = root_span("http:kv", tags={"path": "/v1/kv/a"})
        assert current_context().span_id == root.span_id
        child = child_span("raft-apply")
        assert child is not None
        assert child.trace_id == root.trace_id
        assert child.parent_id == root.span_id
        grand = child_span("fsm:kvs")
        assert grand.parent_id == child.span_id
        finish_span(grand)
        finish_span(child)
        # children finished, root still open: nothing promoted yet
        assert obs_trace.tracer.traces() == []
        finish_span(root)
        assert current_context() is None
        traces = obs_trace.tracer.traces()
        assert len(traces) == 1
        t = traces[0]
        assert t["TraceID"] == root.trace_id
        names = [s["Name"] for s in t["Spans"]]
        assert names == ["fsm:kvs", "raft-apply", "http:kv"]
        by_id = {s["SpanID"]: s for s in t["Spans"]}
        assert by_id[grand.span_id]["ParentID"] == child.span_id
        assert by_id[root.span_id]["ParentID"] is None
        assert all(s["DurationMs"] >= 0 for s in t["Spans"])

    def test_child_without_context_is_none(self):
        assert current_context() is None
        assert child_span("orphan") is None
        finish_span(None)  # tolerated

    def test_error_capture(self):
        root = root_span("http:kv")
        try:
            raise ValueError("boom")
        except ValueError as e:
            finish_span(root, exc=e)
        t = obs_trace.tracer.traces()[0]
        assert t["Spans"][0]["Error"] == "ValueError: boom"

    def test_take_and_ingest_stitch_remote_spans(self):
        """The backhaul round-trip: a server-side tracer's spans for a
        wire parent move (take) into the caller's tracer (ingest) and
        land in the caller's promoted trace."""
        remote = Tracer()
        remote.node_name = "srv1"
        caller_root = root_span("http:kv")
        wire_ctx = SpanContext(caller_root.trace_id, caller_root.span_id)
        # remote side: a server span under the wire parent, recorded
        # into the remote process's tracer
        srv = obs_trace.Span(remote, "rpc:Server.Apply", parent=wire_ctx)
        srv.finish()
        # server spans never promote on the remote node
        assert remote.traces() == []
        backhauled = remote.take(caller_root.trace_id)
        assert len(backhauled) == 1
        obs_trace.tracer.ingest(backhauled)
        finish_span(caller_root)
        t = obs_trace.tracer.traces()[0]
        names = {s["Name"] for s in t["Spans"]}
        assert names == {"rpc:Server.Apply", "http:kv"}

    def test_ring_bounded(self):
        for i in range(RING_TRACES + 10):
            finish_span(root_span(f"r{i}"))
        traces = obs_trace.tracer.traces(limit=10_000)
        assert len(traces) == RING_TRACES
        # newest first
        assert traces[0]["Spans"][0]["Name"] == f"r{RING_TRACES + 9}"

    def test_server_span_finish_restores_context(self):
        ctx = SpanContext("t" * 16, "s" * 16)
        span = server_span("rpc:X", ctx)
        assert current_context().span_id == span.span_id
        span.finish()
        # restored to the pre-span context (None here)
        assert current_context() is None


def _ring(rows):
    """list-of-lists stand-in for the drained device array."""
    return [list(r) for r in rows]


class TestFlightRecorder:
    def _row(self, rnd, **kw):
        base = {c: 0 for c in FLIGHT_COLS}
        base["round"] = rnd
        base.update(kw)
        return [base[c] for c in FLIGHT_COLS]

    def test_ingest_extracts_new_rows_in_order(self):
        m = Metrics()
        rec = FlightRecorder(metrics=m)
        ring = [self._row(i, probes=i + 1) for i in range(4)]
        assert rec.ingest(_ring(ring), 4) == 4
        tl = rec.timeline()
        assert [r["round"] for r in tl] == [0, 1, 2, 3]
        assert rec.summary()["probes"] == 1 + 2 + 3 + 4
        # re-drain with no progress: nothing new
        assert rec.ingest(_ring(ring), 4) == 0

    def test_wraparound_order(self):
        m = Metrics()
        rec = FlightRecorder(metrics=m)
        # ring of 4, cursor at 6: rows 2..5 live at slots 2,3,0,1
        ring = [self._row(4), self._row(5), self._row(2), self._row(3)]
        assert rec.ingest(_ring(ring), 6) == 4
        assert [r["round"] for r in rec.timeline()] == [2, 3, 4, 5]

    def test_overflow_accounted(self):
        m = Metrics()
        rec = FlightRecorder(metrics=m)
        rec.ingest(_ring([self._row(0)]), 1)
        # 9 new rounds through a 1-row ring: 8 lost
        rec.ingest(_ring([self._row(9)]), 10)
        s = rec.summary()
        assert s["rows_overflowed"] == 8
        assert s["rows_recorded"] == 10
        assert rec.last_cursor == 10

    def test_registry_folding(self):
        m = Metrics()
        rec = FlightRecorder(metrics=m)
        rec.ingest(_ring([self._row(0, probes=3, members=7),
                          self._row(1, probes=2, members=8)]), 2)
        snap = m.snapshot()
        counters = {}
        gauges = {}
        for iv in snap:
            for k, v in iv["Counters"].items():
                counters[k] = counters.get(k, 0) + v["sum"]
            gauges.update(iv["Gauges"])
        assert counters["consul.flight.probes"] == 5
        assert gauges["consul.flight.members"] == 8
        assert gauges["consul.flight.round"] == 1


class TestKernelFlight:
    """CPU execution of the jitted round with the recorder enabled."""

    def _setup(self, steps):
        import jax
        import jax.numpy as jnp

        from consul_tpu.gossip.kernel import NEVER, init_state
        from consul_tpu.gossip.params import SwimParams

        p = SwimParams(n=64, slots=16)
        state = init_state(p)
        key = jax.random.PRNGKey(0)
        fail = jnp.full((p.n,), int(NEVER), jnp.int32).at[7].set(3)
        return p, state, key, fail

    def test_flight_does_not_change_dynamics(self):
        """Bit-identical SwimState with and without the recorder: the
        collect branch must be observation only."""
        import numpy as np

        from consul_tpu.gossip.kernel import init_flight, run_rounds

        steps = 50
        p, state, key, fail = self._setup(steps)
        base, _ = run_rounds(state, key, fail, p, steps=steps)
        # run_rounds donates `state`; rebuild it for the second run.
        _, state, _, _ = self._setup(steps)
        (with_fl, fl), _ = run_rounds(state, key, fail, p, steps=steps,
                                      flight=init_flight(64))
        for name in base._fields:
            a, b = getattr(base, name), getattr(with_fl, name)
            assert np.array_equal(np.asarray(a), np.asarray(b)), name
        assert int(fl.cursor) == steps

    def test_flight_rows_content(self):
        import numpy as np

        from consul_tpu.gossip.kernel import init_flight, run_rounds

        # 100 rounds: enough for the round-3 failure's suspicion window
        # to expire and the dead verdict to disseminate.
        steps = 100
        p, state, key, fail = self._setup(steps)
        R = 128
        (state, fl), _ = run_rounds(state, key, fail, p, steps=steps,
                                    flight=init_flight(R))
        m = Metrics()
        rec = FlightRecorder(metrics=m)
        assert rec.ingest(np.asarray(fl.rows), int(fl.cursor)) == steps
        tl = rec.timeline()
        assert [r["round"] for r in tl] == list(range(steps))
        s = rec.summary()
        assert s["probes"] > 0                      # probing happened
        assert s["dead_events"] >= 1                # node 7 died
        assert tl[-1]["members"] == 63              # and left the cluster
        assert tl[0]["members"] == 64
        assert all(len(r) == N_COLS for r in np.asarray(fl.rows))


# One sample line of the text exposition format (0.0.4): name, optional
# labels (unused here), a float value, optional timestamp (unused).
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})?"
    r" (-?\d+(\.\d+)?([eE][+-]?\d+)?|[+-]?Inf|NaN)( \d+)?$")
_TYPE_RE = re.compile(
    r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|summary|histogram|untyped)$")


def _validate_prom(text):
    """Strict-enough text-format validator: every line is a TYPE/HELP
    comment, a sample, or blank; every sample's metric name was
    declared by a preceding TYPE line (summaries declare their _count
    / _sum children, histograms additionally their _bucket series)."""
    declared = set()
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            assert _TYPE_RE.match(line), f"bad TYPE line: {line!r}"
            name, kind = line.split()[2], line.split()[3]
            declared.add(name)
            if kind in ("summary", "histogram"):
                declared.update({name + "_count", name + "_sum"})
            if kind == "histogram":
                declared.add(name + "_bucket")
            continue
        if line.startswith("#"):
            continue
        assert _SAMPLE_RE.match(line), f"bad sample line: {line!r}"
        mname = line.split("{")[0].split(" ")[0]
        assert mname in declared, f"undeclared metric: {mname}"
    assert text.endswith("\n")
    # Double validation with the strict checker the obs-smoke gate runs
    # (tools/check_prom.py): histogram ABI, label escapes, duplicates.
    from tools.check_prom import check_text
    assert check_text(text) == []
    return True


class TestPrometheus:
    def test_sanitize(self):
        assert sanitize("consul.rpc.query") == "consul_rpc_query"
        assert sanitize("1weird-name") == "_1weird_name"

    def test_render_parses_with_flight_series(self):
        m = Metrics()
        m.incr_counter(("consul", "rpc", "query"), 2)
        m.incr_counter(("consul", "rpc", "query"), 3)
        m.set_gauge(("consul", "sessions"), 4.5)
        m.add_sample(("consul", "fsm", "kvs"), 1.25)
        m.add_sample(("consul", "fsm", "kvs"), 0.75)
        rec = FlightRecorder(metrics=m)
        row = {c: 0 for c in FLIGHT_COLS}
        row.update(round=5, probes=9, members=64)
        rec.ingest([[row[c] for c in FLIGHT_COLS]], 1)

        text = render_prometheus(m.snapshot())
        assert _validate_prom(text)
        assert "# TYPE consul_rpc_query counter" in text
        assert "consul_rpc_query 5" in text
        assert "consul_sessions 4.5" in text
        # samples render as a time summary in seconds
        assert "# TYPE consul_fsm_kvs_seconds summary" in text
        assert "consul_fsm_kvs_seconds_count 2" in text
        assert "consul_fsm_kvs_seconds_sum 0.002" in text
        # flight series present alongside the telemetry ones
        assert "# TYPE consul_flight_probes counter" in text
        assert "consul_flight_probes 9" in text
        assert "consul_flight_members 64" in text

    def test_render_empty_snapshot(self):
        # an empty exposition body is valid (no families, no samples)
        assert render_prometheus([]) == ""

    def test_escape_label_value(self):
        from consul_tpu.obs.prom import escape_label_value
        assert escape_label_value('a"b') == r'a\"b'
        assert escape_label_value("a\\b") == r"a\\b"
        assert escape_label_value("a\nb") == r"a\nb"

    def test_help_lines_present_and_escaped(self):
        m = Metrics()
        m.incr_counter(("consul", "rpc", "query"), 1)
        text = render_prometheus(m.snapshot())
        assert "# HELP consul_rpc_query " in text
        assert _validate_prom(text)

    def test_counter_gauge_name_collision_dedupes(self):
        """In-process plane + agent put consul.flight.* in the registry
        as BOTH counters (FlightRecorder) and gauges (fold_summary
        mirror); one family per name must survive, counter first."""
        m = Metrics()
        m.incr_counter(("consul", "flight", "probes"), 5)
        m.set_gauge(("consul", "flight", "probes"), 5)
        text = render_prometheus(m.snapshot())
        assert text.count("# TYPE consul_flight_probes ") == 1
        assert "# TYPE consul_flight_probes counter" in text
        assert _validate_prom(text)

    def test_histogram_families_render(self):
        """Cumulative histogram exposition: ascending le buckets, the
        mandatory +Inf bucket equal to _count, _sum, strict-checker
        clean."""
        from consul_tpu.obs.hist import LATENCY_BUCKETS, HistRecorder
        import numpy as np
        rec = HistRecorder()
        detect = np.zeros(LATENCY_BUCKETS, np.int64)
        detect[3] = 2
        detect[70] = 1
        rec.ingest({"detect": detect})
        text = render_prometheus([], histograms=rec.families())
        assert _validate_prom(text)
        n = "consul_swim_detection_latency_rounds"
        assert f"# TYPE {n} histogram" in text
        assert f'{n}_bucket{{le="2"}} 0' in text
        assert f'{n}_bucket{{le="4"}} 2' in text      # the two 3-round obs
        assert f'{n}_bucket{{le="64"}} 2' in text
        assert f'{n}_bucket{{le="128"}} 3' in text
        assert f'{n}_bucket{{le="+Inf"}} 3' in text
        assert f"{n}_sum {3 * 2 + 70}" in text
        assert f"{n}_count 3" in text


class TestHistRecorder:
    def _bank(self, **at):
        import numpy as np

        from consul_tpu.obs.hist import LATENCY_BUCKETS
        b = np.zeros(LATENCY_BUCKETS, np.int64)
        for i, c in at.items():
            b[int(i)] = c
        return b

    def test_ingest_returns_deltas(self):
        from consul_tpu.obs.hist import HistRecorder
        rec = HistRecorder()
        d1 = rec.ingest({"detect": self._bank(**{"5": 2})})
        assert d1["detect"][5] == 2
        d2 = rec.ingest({"detect": self._bank(**{"5": 3, "9": 1})})
        assert d2["detect"][5] == 1 and d2["detect"][9] == 1
        assert rec.counts("detect")[5] == 3  # cumulative view kept

    def test_percentile_matches_crossval_pct(self):
        """The bank reconstructs the exact multiset below overflow, so
        percentile() must equal numpy's percentile of the raw values —
        the same ``pct`` the crossval oracle gates on."""
        import numpy as np

        from consul_tpu.obs.hist import HistRecorder
        values = [3, 3, 7, 7, 7, 12, 40, 41, 90]
        bank = self._bank()
        for v in values:
            bank[v] += 1
        rec = HistRecorder()
        rec.ingest({"detect": bank})
        for q in (50, 90, 99):
            assert rec.percentile("detect", q) == float(
                np.percentile(np.asarray(values), q))
        assert rec.percentile("dwell", 50) is None  # no data

    def test_spread_family_log2_edges(self):
        import numpy as np

        from consul_tpu.obs.hist import SPREAD_BUCKETS, HistRecorder
        bank = np.zeros(SPREAD_BUCKETS, np.int64)
        bank[0] = 1   # 0 members
        bank[3] = 2   # bit_length 3: 4..7 members
        rec = HistRecorder()
        rec.ingest({"spread": bank})
        fam = [f for f in rec.families()
               if f["name"] == "consul.swim.spread_members"][0]
        by_le = dict(fam["buckets"])
        assert by_le["0"] == 1
        assert by_le["3"] == 1    # bit_length <= 2 -> only the zero
        assert by_le["7"] == 3    # bit_length <= 3 includes both
        assert fam["count"] == 3
        assert fam["sum"] == 0 + 2 * 4  # floors: 0 and 2^(3-1)

    def test_summary_shape(self):
        from consul_tpu.obs.hist import HistRecorder
        rec = HistRecorder()
        rec.ingest({"detect": self._bank(**{"8": 4})})
        s = rec.summary()
        assert s["detect"] == {"count": 4, "p50_rounds": 8.0,
                               "p99_rounds": 8.0}
        assert s["refute"]["count"] == 0
        assert s["refute"]["p99_rounds"] is None


class TestSloTracker:
    def test_attainment_and_burn(self):
        from consul_tpu.obs.slo import SloTracker
        t = SloTracker(objective_rounds=10, attainment_target=0.9)
        # 8 within (buckets 0..10), 2 beyond
        delta = [0] * 64
        delta[5] = 4
        delta[10] = 4
        delta[30] = 2
        assert t.observe(delta) == 10
        s = t.snapshot()
        assert s["detections"] == 10
        assert s["attainment"] == 0.8
        assert s["window_attainment"] == 0.8
        assert s["burn_rate"] == pytest.approx((1 - 0.8) / (1 - 0.9))

    def test_empty_snapshot_and_validation(self):
        from consul_tpu.obs.slo import SloTracker
        t = SloTracker(objective_rounds=5)
        assert t.observe([0] * 8) == 0          # empty drain: no entry
        s = t.snapshot()
        assert s["attainment"] is None
        assert s["burn_rate"] == 0.0
        with pytest.raises(ValueError):
            SloTracker(objective_rounds=-1)
        with pytest.raises(ValueError):
            SloTracker(objective_rounds=1, attainment_target=1.0)

    def test_window_rolls(self):
        from consul_tpu.obs.slo import SloTracker
        t = SloTracker(objective_rounds=0, window=2)
        bad = [0, 5]     # all beyond a 0-round objective... bucket 1 = 1 round
        good = [5, 0]    # all within (bucket 0)
        t.observe(bad)
        t.observe(good)
        t.observe(good)  # window now holds the two good drains only
        s = t.snapshot()
        assert s["window_attainment"] == 1.0
        assert s["attainment"] == pytest.approx(10 / 15)


class TestKernelHist:
    """CPU execution of the jitted round with the observatory enabled."""

    def test_hist_does_not_change_dynamics(self):
        """Bit-identical SwimState with and without the banks: the
        observation block reads verdict-round state, never writes it."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from consul_tpu.gossip.kernel import (
            NEVER, init_hist, init_state, run_rounds)
        from consul_tpu.gossip.params import SwimParams

        p = SwimParams(n=64, slots=16)
        key = jax.random.PRNGKey(0)
        fail = jnp.full((p.n,), int(NEVER), jnp.int32).at[7].set(3)
        base, _ = run_rounds(init_state(p), key, fail, p, steps=100)
        (with_h, hb), _ = run_rounds(init_state(p), key, fail, p,
                                     steps=100, hist=init_hist())
        for name in base._fields:
            assert np.array_equal(np.asarray(getattr(base, name)),
                                  np.asarray(getattr(with_h, name))), name
        assert int(np.asarray(hb.detect).sum()) == 1
        assert int(np.asarray(hb.dwell).sum()) == 1

    @pytest.mark.slow

    def test_detect_bank_matches_crossval_oracle(self):
        """ISSUE 4 acceptance core: percentiles computed from the
        in-kernel detect bank equal the crossval oracle's ``pct`` over
        the SAME run's trace-derived latencies, exactly."""
        import jax.numpy as jnp
        import numpy as np

        from consul_tpu.gossip.crossval import kernel_event_latencies
        from consul_tpu.gossip.kernel import (
            NEVER, init_hist, init_state, run_rounds)
        from consul_tpu.gossip.params import lan_profile
        from consul_tpu.obs.hist import HistRecorder
        import jax

        p = lan_profile(512, slots=16)
        steps, seed = 300, 5
        fail_at = {int(i * 37 % 512): 10 + 20 * i for i in range(6)}
        fail = np.full(p.n, int(NEVER), np.int32)
        for v, t in fail_at.items():
            fail[v] = t
        # crossval derives latencies from the round trace of its own
        # run; replicate that run exactly (same key construction) with
        # the banks threaded through.
        (st, hb), _ = run_rounds(init_state(p), jax.random.key(seed),
                                 jnp.asarray(fail), p, steps,
                                 hist=init_hist())
        lats, _, _, _ = kernel_event_latencies(p, fail_at, steps, seed)
        rec = HistRecorder()
        rec.ingest({"detect": np.asarray(hb.detect)})
        assert len(lats) == len(fail_at)
        assert int(rec.counts("detect").sum()) == len(lats)
        a = np.asarray(lats)
        for q in (50, 90, 99):
            assert rec.percentile("detect", q) == float(np.percentile(a, q))


class TestScenarioObs:
    """Scenario dimension of the observatory (gossip/nemesis.py):
    scenario-attributed ingest, labeled Prometheus families, the
    per-scenario SLO board, and the exposition contract (one TYPE per
    family, per-labelset bucket ladders)."""

    def _recorder_with_scenarios(self):
        import numpy as np

        from consul_tpu.obs.hist import HistRecorder
        rec = HistRecorder()
        det = np.zeros(256, dtype=np.int64)
        det[50] = 3
        rec.ingest({"detect": det}, scenario="block_kill")
        det2 = det.copy()
        det2[70] = 2
        rec.ingest({"detect": det2}, scenario="flapping")
        return rec, det, det2

    def test_scenario_ingest_attributes_deltas(self):
        rec, det, det2 = self._recorder_with_scenarios()
        # aggregate = all deltas; each scenario = deltas while active
        assert int(rec.counts("detect").sum()) == 5
        assert int(rec.counts("detect@block_kill").sum()) == 3
        assert int(rec.counts("detect@flapping").sum()) == 2
        assert rec.scenarios() == ["block_kill", "flapping"]
        # the wrap bookkeeping stays keyed by the bare bank name: the
        # flapping delta was det2 - det, not det2 - 0
        assert int(rec.counts("detect@flapping")[70]) == 2
        assert int(rec.counts("detect@flapping")[50]) == 0

    def test_scenario_families_and_summary(self):
        rec, _, _ = self._recorder_with_scenarios()
        fams = [f for f in rec.families()
                if f["name"].endswith("detection_latency_rounds")]
        # unlabeled aggregate first, then one labeled family per scenario
        assert "labels" not in fams[0]
        assert [f.get("labels") for f in fams[1:]] == [
            {"scenario": "block_kill"}, {"scenario": "flapping"}]
        assert fams[0]["count"] == 5
        assert fams[1]["count"] == 3
        s = rec.summary("flapping")
        assert s["detect"]["count"] == 2
        assert s["detect"]["p50_rounds"] == 70.0
        assert rec.summary()["detect"]["count"] == 5

    def test_scenario_labeled_exposition_is_strict_clean(self):
        from tools.check_prom import _iter_series, check_text
        rec, _, _ = self._recorder_with_scenarios()
        text = render_prometheus([], histograms=rec.families())
        assert check_text(text) == []
        # exactly one TYPE line per family name despite three variants
        assert text.count(
            "# TYPE consul_swim_detection_latency_rounds ") == 1
        labeled = [(n, lab) for n, lab in _iter_series(text)
                   if lab.get("scenario") == "block_kill"]
        assert any(n.endswith("_bucket") for n, _ in labeled)
        assert any(n.endswith("_count") for n, _ in labeled)

    def test_slo_board_lazy_per_scenario(self):
        from consul_tpu.obs.slo import SloBoard
        board = SloBoard(100, attainment_target=0.9)
        assert board.snapshot() == {}
        assert board.observe("", [1]) == 0          # unattributed: dropped
        assert board.observe("block_kill", [0] * 50 + [4]) == 4
        assert board.observe("flapping", [0] * 150 + [2]) == 2
        snap = board.snapshot()
        assert sorted(snap) == ["block_kill", "flapping"]
        assert snap["block_kill"]["attainment"] == 1.0
        assert snap["block_kill"]["burn_rate"] == 0.0
        # flapping latencies (150 rounds) blow the 100-round objective
        assert snap["flapping"]["attainment"] == 0.0
        assert snap["flapping"]["burn_rate"] == pytest.approx(10.0)
