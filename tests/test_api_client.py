"""Client SDK tests against a live agent (reference tier: api/*_test.go,
which drives a forked consul binary; here the in-process AgentHarness
plays that role)."""

import threading
import time

import pytest

from consul_tpu.api import (
    Client, Config, KVPair, Lock, LockError, QueryOptions, Semaphore)
from tests.test_agent_http import AgentHarness


@pytest.fixture(scope="module")
def harness():
    h = AgentHarness().start()
    yield h
    h.stop()


@pytest.fixture()
def client(harness):
    host, port = harness.agent.http.addr
    c = Client(Config(address=f"{host}:{port}"))
    yield c
    c.close()


class TestKV:
    def test_put_get_delete(self, client):
        assert client.kv.put(KVPair(key="sdk/a", value=b"hello", flags=42))
        pair, meta = client.kv.get("sdk/a")
        assert pair.value == b"hello" and pair.flags == 42
        assert meta.last_index > 0 and meta.known_leader
        assert client.kv.delete("sdk/a")
        pair, _ = client.kv.get("sdk/a")
        assert pair is None

    def test_list_keys_cas(self, client):
        for k in ("sdk/l/x", "sdk/l/y", "sdk/l/z/deep"):
            client.kv.put(KVPair(key=k, value=b"v"))
        pairs, _ = client.kv.list("sdk/l/")
        assert [p.key for p in pairs] == ["sdk/l/x", "sdk/l/y", "sdk/l/z/deep"]
        keys, _ = client.kv.keys("sdk/l/", separator="/")
        assert keys == ["sdk/l/x", "sdk/l/y", "sdk/l/z/"]
        pair, _ = client.kv.get("sdk/l/x")
        assert client.kv.cas(KVPair(key="sdk/l/x", value=b"new",
                                    modify_index=pair.modify_index))
        # stale index loses
        assert not client.kv.cas(KVPair(key="sdk/l/x", value=b"zzz",
                                        modify_index=pair.modify_index))
        client.kv.delete_tree("sdk/l/")
        pairs, _ = client.kv.list("sdk/l/")
        assert pairs == []

    def test_blocking_query_wakes(self, client):
        client.kv.put(KVPair(key="sdk/watch", value=b"1"))
        pair, meta = client.kv.get("sdk/watch")

        def writer():
            time.sleep(0.2)
            c2 = Client(Config(address=client.config.address))
            c2.kv.put(KVPair(key="sdk/watch", value=b"2"))
            c2.close()

        threading.Thread(target=writer, daemon=True).start()
        t0 = time.monotonic()
        pair2, _ = client.kv.get("sdk/watch", QueryOptions(
            wait_index=meta.last_index, wait_time=10.0))
        elapsed = time.monotonic() - t0
        assert pair2.value == b"2"
        assert elapsed < 5.0  # woke on write, not timeout


class TestAgentCatalogHealth:
    def test_agent_surface(self, client):
        assert client.agent.node_name() == "node1"
        client.agent.service_register({
            "ID": "sdkweb", "Name": "sdkweb", "Port": 80,
            "Check": {"TTL": "30s"}})
        assert "sdkweb" in client.agent.services()
        client.agent.pass_ttl("service:sdkweb", note="ok")
        assert client.agent.checks()["service:sdkweb"]["Status"] == "passing"
        nodes, _ = client.health.service("sdkweb", passing_only=True)
        deadline = time.monotonic() + 5
        while not nodes and time.monotonic() < deadline:
            time.sleep(0.1)
            nodes, _ = client.health.service("sdkweb", passing_only=True)
        assert nodes and nodes[0]["Service"]["ID"] == "sdkweb"
        client.agent.fail_ttl("service:sdkweb")
        client.agent.service_deregister("sdkweb")

    def test_catalog_surface(self, client):
        assert client.catalog.datacenters() == ["dc1"]
        nodes, meta = client.catalog.nodes()
        assert any(n["Node"] == "node1" for n in nodes)
        services, _ = client.catalog.services()
        assert "consul" in services
        entries, _ = client.catalog.service("consul")
        assert entries and entries[0]["ServicePort"] == 8300

    def test_status_surface(self, client):
        assert client.status.leader()
        assert client.status.peers()


class TestSessions:
    def test_session_lifecycle(self, client):
        sid = client.session.create({"Name": "sdk", "TTL": "30s"})
        info, _ = client.session.info(sid)
        assert info["Name"] == "sdk"
        sessions, _ = client.session.list()
        assert any(s["ID"] == sid for s in sessions)
        renewed = client.session.renew(sid)
        assert renewed["ID"] == sid
        client.session.destroy(sid)
        info, _ = client.session.info(sid)
        assert info is None


class TestLock:
    def test_acquire_contend_release(self, client, harness):
        host, port = harness.agent.http.addr
        l1 = Lock(client, "sdk/locks/leader", value=b"n1")
        lost1 = l1.acquire()
        assert lost1 is not None and l1.is_held

        # second contender blocks until release
        c2 = Client(Config(address=f"{host}:{port}"))
        l2 = Lock(c2, "sdk/locks/leader", value=b"n2", wait_time=1.0)
        got2 = {}

        def contender():
            got2["lost"] = l2.acquire()

        t = threading.Thread(target=contender, daemon=True)
        t.start()
        time.sleep(0.5)
        assert not l2.is_held  # still blocked
        l1.release()
        t.join(15)
        assert l2.is_held and got2["lost"] is not None
        l2.release()
        c2.close()

    def test_lost_on_session_destroy(self, client, harness):
        host, port = harness.agent.http.addr
        lock = Lock(client, "sdk/locks/ephemeral", wait_time=1.0)
        lost = lock.acquire()
        assert lock.is_held
        # kill the session out from under the lock
        c2 = Client(Config(address=f"{host}:{port}"))
        c2.session.destroy(lock.session)
        assert lost.wait(10), "lost-lock event did not fire"
        lock.is_held = False
        c2.close()

    def test_flag_mismatch_rejected(self, client):
        client.kv.put(KVPair(key="sdk/locks/plain", value=b"x"))
        lock = Lock(client, "sdk/locks/plain", wait_time=0.5)
        with pytest.raises(LockError):
            lock.acquire()


class TestSemaphore:
    def test_slots(self, client, harness):
        host, port = harness.agent.http.addr
        clients = [Client(Config(address=f"{host}:{port}")) for _ in range(3)]
        sems = [Semaphore(c, "sdk/sema", limit=2, wait_time=1.0)
                for c in clients]
        assert sems[0].acquire() is not None
        assert sems[1].acquire() is not None

        got3 = {}

        def third():
            got3["lost"] = sems[2].acquire()

        t = threading.Thread(target=third, daemon=True)
        t.start()
        time.sleep(0.5)
        assert not sems[2].is_held  # both slots taken
        sems[0].release()
        t.join(15)
        assert sems[2].is_held
        sems[1].release()
        sems[2].release()
        for c in clients:
            c.close()

    def test_limit_validation(self, client):
        with pytest.raises(Exception):
            Semaphore(client, "sdk/sema2", limit=0)
