"""Chaos subsystem: broker units, dial backoff, shutdown-under-fault.

Three layers, mirroring the gossip plane's nemesis tier:

  * broker units — the virtual clock, directional link faults, and the
    fsync wrapper are deterministic under a fixed seed;
  * dial backoff (rpc/pool.py satellite) — repeated dial failures back
    off exponentially with jitter, fail fast inside the window, and
    reset on the first successful dial;
  * shutdown-under-fault regressions — the PR-13 lifecycle fixes
    (LeaderDuties.drain, _fail_abandoned future hygiene, barrier-task
    cleanup) hold while a fault is actively injected: a flapping
    leader and a mid-fsync-stall stop must leave no pending futures,
    no undrained leader tasks, and no durability waiters behind.

The campaign smoke test runs one real scenario end-to-end (cluster,
fault, linearizability gate, CHAOS verdict) with the CI seed.
"""

from __future__ import annotations

import asyncio
import time

import pytest

from consul_tpu.chaos.broker import FaultBroker, FaultClock
from consul_tpu.chaos.scenarios import CATALOG, FAST_SCENARIOS, ChaosParams
from consul_tpu.consensus.raft import (
    MemoryTransport, RaftConfig, TransportError)
from consul_tpu.rpc.pool import (
    DIAL_BACKOFF_CAP, DIAL_BACKOFF_JITTER, ConnPool)
from consul_tpu.rpc.server import RPCServer
from consul_tpu.server.server import Server, ServerConfig


def fast_raft(**kw) -> RaftConfig:
    base = dict(heartbeat_interval=0.02, election_timeout_min=0.1,
                election_timeout_max=0.2, rpc_timeout=0.05)
    base.update(kw)
    return RaftConfig(**base)


def make_faulty_servers(n=3, seed=7, **raft_kw):
    broker = FaultBroker(seed=seed)
    tr = MemoryTransport(faults=broker)
    names = [f"s{i}" for i in range(n)]
    servers = [Server(ServerConfig(node_name=nm, peers=names,
                                   raft=fast_raft(**raft_kw),
                                   faults=broker.node(nm)), transport=tr)
               for nm in names]
    return broker, tr, servers


async def start_and_elect(servers):
    for s in servers:
        await s.start()
    deadline = asyncio.get_event_loop().time() + 5
    while asyncio.get_event_loop().time() < deadline:
        leaders = [s for s in servers if s.is_leader()]
        if len(leaders) == 1:
            return leaders[0]
        await asyncio.sleep(0.01)
    raise AssertionError("no leader")


def run(coro):
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# FaultClock
# ---------------------------------------------------------------------------


class TestFaultClock:
    def test_identity_by_default(self):
        t = [100.0]
        c = FaultClock(base=lambda: t[0])
        assert c.monotonic() == pytest.approx(100.0)
        t[0] += 5.0
        assert c.monotonic() == pytest.approx(105.0)
        assert c.drift() == pytest.approx(0.0)

    def test_rate_scales_from_anchor(self):
        t = [0.0]
        c = FaultClock(base=lambda: t[0])
        t[0] = 10.0            # 10s at rate 1
        c.set_rate(3.0)
        t[0] = 12.0            # +2s real at rate 3 = +6s virtual
        assert c.monotonic() == pytest.approx(16.0)
        c.set_rate(1.0)        # re-anchors: no discontinuity
        before = c.monotonic()
        t[0] = 13.0
        assert c.monotonic() == pytest.approx(before + 1.0)
        assert c.drift() == pytest.approx(4.0)

    def test_jump_is_discontinuous(self):
        t = [50.0]
        c = FaultClock(base=lambda: t[0])
        c.jump(0.25)
        assert c.monotonic() == pytest.approx(50.25)
        c.jump(-0.1)
        assert c.monotonic() == pytest.approx(50.15)
        assert c.drift() == pytest.approx(0.15)

    def test_two_clocks_same_script_agree(self):
        def script(c, t):
            out = [c.monotonic()]
            t[0] += 1.0
            c.set_rate(2.5)
            t[0] += 2.0
            out.append(c.monotonic())
            c.jump(0.5)
            out.append(c.monotonic())
            return out
        ta, tb = [0.0], [0.0]
        assert script(FaultClock(base=lambda: ta[0]), ta) == \
            script(FaultClock(base=lambda: tb[0]), tb)


# ---------------------------------------------------------------------------
# Broker links + fsync wrapper
# ---------------------------------------------------------------------------


class TestBrokerLinks:
    def test_full_drop_is_directional(self):
        async def main():
            broker = FaultBroker(seed=1)
            broker.set_link("a", "b", drop=1.0)
            with pytest.raises(TransportError):
                await broker.on_message("a", "b")
            await broker.on_message("b", "a")  # reverse leg clean
        run(main())

    def test_delay_sleeps(self):
        async def main():
            broker = FaultBroker(seed=1)
            broker.set_link("a", "b", delay_s=0.05)
            t0 = time.monotonic()
            await broker.on_message("a", "b")
            assert time.monotonic() - t0 >= 0.04
        run(main())

    def test_isolate_and_rejoin(self):
        async def main():
            broker = FaultBroker(seed=1)
            for nm in ("a", "b", "c"):
                broker.node(nm)
            broker.isolate("a")
            with pytest.raises(TransportError):
                await broker.on_message("a", "b")
            with pytest.raises(TransportError):
                await broker.on_message("c", "a")
            await broker.on_message("b", "c")  # third parties untouched
            broker.rejoin("a")
            await broker.on_message("a", "b")
            await broker.on_message("c", "a")
        run(main())

    def test_probabilistic_drop_deterministic_per_seed(self):
        async def outcomes(seed):
            broker = FaultBroker(seed=seed)
            broker.set_link("a", "b", drop=0.5)
            out = []
            for _ in range(32):
                try:
                    await broker.on_message("a", "b")
                    out.append(True)
                except TransportError:
                    out.append(False)
            return out
        a = run(outcomes(42))
        b = run(outcomes(42))
        assert a == b
        assert True in a and False in a  # 0.5 actually flips both ways

    def test_clear_links_heals(self):
        async def main():
            broker = FaultBroker(seed=1)
            broker.set_link("a", "b", drop=1.0)
            broker.clear_links()
            await broker.on_message("a", "b")
        run(main())


class TestWrapFsync:
    def test_stall_delays_then_syncs(self):
        broker = FaultBroker(seed=3)
        nf = broker.node("n")
        calls = []
        wrapped = nf.wrap_fsync(lambda: calls.append(1))
        nf.fsync_stall_s = 0.05
        t0 = time.monotonic()
        wrapped()
        assert time.monotonic() - t0 >= 0.04
        assert calls == [1]
        nf.fsync_stall_s = 0.0  # knobs are live, not bind-time
        t0 = time.monotonic()
        wrapped()
        assert time.monotonic() - t0 < 0.04

    def test_injected_error_skips_sync(self):
        broker = FaultBroker(seed=3)
        nf = broker.node("n")
        calls = []
        wrapped = nf.wrap_fsync(lambda: calls.append(1))
        nf.fsync_err_p = 1.0
        with pytest.raises(OSError):
            wrapped()
        assert calls == []


# ---------------------------------------------------------------------------
# Scenario catalog hygiene
# ---------------------------------------------------------------------------


class TestScenarioCatalog:
    def test_catalog_keys_match_fault_field(self):
        for name, p in CATALOG.items():
            assert p.fault == name

    def test_fast_subset_is_in_catalog(self):
        assert set(FAST_SCENARIOS) <= set(CATALOG)

    def test_unknown_fault_rejected(self):
        with pytest.raises(ValueError):
            ChaosParams(fault="split_brain_wish")  # noqa: K02

    def test_window_must_fit_run(self):
        with pytest.raises(ValueError):
            ChaosParams(fault="clock_jump", start=1.0, stop=0.5)


# ---------------------------------------------------------------------------
# Dial backoff (rpc/pool.py satellite)
# ---------------------------------------------------------------------------


class TestDialBackoff:
    def test_fail_fast_inside_window(self, monkeypatch):
        async def main():
            dials = []

            async def refuse(host, port):
                dials.append((host, port))
                raise ConnectionRefusedError("refused")

            monkeypatch.setattr(asyncio, "open_connection", refuse)
            pool = ConnPool()
            addr = "127.0.0.1:59999"
            # rpc() retries once; the retry must hit the backoff gate,
            # not the socket.
            with pytest.raises(OSError):
                await pool.rpc(addr, "Status.Ping", {}, timeout=0.5)
            assert len(dials) == 1
            assert pool.dial_backoff_remaining(addr) > 0.0
            with pytest.raises(ConnectionError, match="dial backoff"):
                await pool._session(addr)
            assert len(dials) == 1  # still no new socket
        run(main())

    def test_exponential_growth_capped(self):
        pool = ConnPool()
        addr = "10.0.0.1:1"
        prev = 0.0
        for i in range(1, 12):
            pool._dial_failed(addr)
            fails, _ = pool._dial_backoff[addr]
            assert fails == i
            rem = pool.dial_backoff_remaining(addr)
            if i >= 7:  # 0.05 * 2^6 = 3.2 > cap: clamped
                lo = DIAL_BACKOFF_CAP * (1.0 - DIAL_BACKOFF_JITTER) - 0.01
                hi = DIAL_BACKOFF_CAP * (1.0 + DIAL_BACKOFF_JITTER) + 0.01
                assert lo <= rem <= hi
            prev = rem
        assert prev <= DIAL_BACKOFF_CAP * (1.0 + DIAL_BACKOFF_JITTER) + 0.01

    def test_success_resets_backoff(self):
        async def main():
            server = await asyncio.start_server(
                lambda r, w: None, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            addr = f"127.0.0.1:{port}"
            pool = ConnPool()
            # Expired backoff window with failure history: one good
            # dial clears the slate.
            pool._dial_backoff[addr] = (5, 0.0)
            await pool._session(addr)
            assert addr not in pool._dial_backoff
            assert pool.dial_backoff_remaining(addr) == 0.0
            await pool.close()
            server.close()
            await server.wait_closed()
        run(main())


# ---------------------------------------------------------------------------
# Fault-filter seams (pool outbound, rpc server inbound)
# ---------------------------------------------------------------------------


class TestFaultFilterSeams:
    def test_pool_outbound_filter_raises(self):
        async def main():
            pool = ConnPool()

            async def cut(addr, method):
                raise TransportError(f"chaos: {addr} {method} dropped")

            pool.fault_filter = cut
            with pytest.raises(TransportError):
                await pool.rpc("127.0.0.1:1", "KVS.Apply", {})
        run(main())

    def test_rpc_server_inbound_filter_becomes_rpc_error(self):
        async def main():
            rpc = RPCServer(None)  # dispatch bails before touching srv

            async def cut(req):
                raise TransportError("chaos: inbound dropped")

            rpc.fault_filter = cut
            resp = await rpc._dispatch({"Method": "Status.Ping"})
            assert "chaos: inbound dropped" in resp["Error"]
        run(main())


# ---------------------------------------------------------------------------
# Shutdown-under-fault regressions (PR-13 lifecycle fixes)
# ---------------------------------------------------------------------------


def _assert_clean_shutdown(servers):
    for s in servers:
        assert s.leader_duties._cancelled == [], \
            f"{s.config.node_name}: undrained leader tasks"
        assert s.raft._pending == {}, \
            f"{s.config.node_name}: abandoned apply futures"
        assert s.raft._durable_waiters == [], \
            f"{s.config.node_name}: abandoned durability waiters"
        assert s._barrier_inflight is None, \
            f"{s.config.node_name}: leaked barrier task"


class TestShutdownUnderFault:
    def test_stop_during_leader_flap(self):
        async def main():
            broker, _, servers = make_faulty_servers()
            leader = await start_and_elect(servers)
            victim = leader.config.node_name
            broker.isolate(victim)
            # Wait for the isolated leader to be deposed (a new term
            # exists it cannot see), then stop everything mid-flap.
            deadline = asyncio.get_event_loop().time() + 5
            while asyncio.get_event_loop().time() < deadline:
                others = [s for s in servers if s is not leader]
                if any(s.is_leader() for s in others):
                    break
                await asyncio.sleep(0.01)
            else:
                raise AssertionError("no re-election under isolation")
            for s in servers:
                await s.stop()
            broker.clear_links()
            _assert_clean_shutdown(servers)
        run(main())

    def test_stop_mid_fsync_stall(self):
        async def main():
            from consul_tpu.structs.structs import (
                DirEntry, KVSOp, KVSRequest)
            broker, _, servers = make_faulty_servers()
            leader = await start_and_elect(servers)
            for s in servers:
                broker.node(s.config.node_name).fsync_stall_s = 0.4
            write = asyncio.ensure_future(leader.kvs.apply(KVSRequest(
                op=KVSOp.SET.value,
                dir_ent=DirEntry(key="stall", value=b"x"))))
            await asyncio.sleep(0.05)  # entry in flight, pump stalled
            t0 = asyncio.get_event_loop().time()
            for s in servers:
                await s.stop()
            # Stop must not wait out the full stall chain to fail the
            # pending apply.  (A hung write turns into TimeoutError and
            # trips the elapsed-time assertion below.)
            with pytest.raises(Exception):
                await asyncio.wait_for(write, timeout=2.0)
            assert asyncio.get_event_loop().time() - t0 < 2.0
            _assert_clean_shutdown(servers)
            for s in servers:
                broker.node(s.config.node_name).fsync_stall_s = 0.0
            # Drain the executor so no stall thread outlives the loop.
            await asyncio.get_event_loop().shutdown_default_executor()
        run(main())


# ---------------------------------------------------------------------------
# Campaign smoke: one real scenario end-to-end with the CI seed.
# ---------------------------------------------------------------------------


class TestCampaignSmoke:
    def test_clock_jump_scenario_passes(self, tmp_path):
        from consul_tpu.chaos.campaign import run_campaign
        report = run_campaign(["clock_jump"], seed=1234,
                              out_dir=str(tmp_path))
        [res] = report["scenarios"]
        assert res["gates"]["linearizable"]
        assert res["gates"]["single_lease_holder"]
        assert res["gates"]["no_deposed_serve"]
        assert res["detection"]["detected"]
        assert report["passed"]
        # The debug bundle is the operator's first stop.
        assert (tmp_path / "clock_jump" / "verdict.json").exists()
        assert (tmp_path / "clock_jump" / "prom.txt").exists()
