"""Fork/exec test harness: run the REAL consul-tpu agent binary.

Parity target: ``testutil/server.go:85-142`` — TestServer writes a JSON
config with a per-instance port block (20000+ range), fork/execs the
real binary found on PATH, and waits for the HTTP API / leader before
handing control to the test.  Here the "binary" is
``python -m consul_tpu.cli.main agent`` run as a subprocess, which
exercises the full stack end-to-end: config files → CLI → agent →
gossip/raft/RPC mesh → HTTP/DNS/IPC listeners.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import struct
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

_PORT_STRIDE = 10
# Keyed off the PID so concurrent test processes (xdist workers, manual
# harness runs, a straggling daemon from a previous suite) land in
# disjoint ranges (the reference uses 20000+idx per instance,
# server.go:85-92; we add per-process spreading).  Each process owns a
# 200-port range = 20 instance blocks.
_PORT_BASE = 21000 + (os.getpid() % 199) * 200
_next_idx = [0]


def _port_block() -> Dict[str, int]:
    """Sequential per-instance port blocks (server.go:85-92)."""
    idx = _next_idx[0]
    _next_idx[0] += 1
    base = _PORT_BASE + idx * _PORT_STRIDE
    return {"http": base, "dns": base + 1, "rpc": base + 2,
            "serf_lan": base + 3, "serf_wan": base + 4, "server": base + 5}


class _Drain:
    """Continuously drain a child's stdout pipe into a buffer.

    A child whose pipe is never read BLOCKS once the 64 KB pipe buffer
    fills — XLA's C++ logging alone can do that (its AOT cache-feature-
    mismatch warnings are ~4 KB EACH), freezing the child's event loop
    mid-write.  This bit as a gossipd daemon that compiled fine, served
    its first probes, then wedged before sending a welcome frame."""

    def __init__(self, pipe) -> None:
        import threading
        self._buf = bytearray()
        self._lock = threading.Lock()

        def pump():
            try:
                # read1, not read: read(n) on a BufferedReader blocks
                # until n bytes OR EOF, so nothing would surface until
                # the child exits — output() must see a LIVE process's
                # writes (e.g. the SIGUSR1 telemetry dump).
                for chunk in iter(lambda: pipe.read1(65536), b""):
                    with self._lock:
                        self._buf += chunk
            except Exception:
                pass

        self._t = threading.Thread(target=pump, daemon=True)
        self._t.start()

    def text(self) -> str:
        with self._lock:
            return self._buf.decode(errors="replace")


class TestServer:
    """One forked agent.  Not a pytest class (helper)."""

    __test__ = False  # keep pytest from collecting it

    def __init__(self, name: str = "bb1", server: bool = True,
                 bootstrap: bool = True, bootstrap_expect: int = 0,
                 retry_join: Optional[List[str]] = None,
                 config_extra: Optional[Dict[str, Any]] = None) -> None:
        self.name = name
        self.ports = _port_block()
        self.tmp = tempfile.TemporaryDirectory(prefix=f"consul-tpu-{name}-")
        cfg: Dict[str, Any] = {
            "node_name": name,
            "server": server,
            "bootstrap": bootstrap and not bootstrap_expect,
            "bootstrap_expect": bootstrap_expect,
            "bind_addr": "127.0.0.1",
            "client_addr": "127.0.0.1",
            "data_dir": os.path.join(self.tmp.name, "data"),
            "ports": self.ports,
            "log_level": "WARN",
        }
        if retry_join:
            cfg["retry_join"] = retry_join
            cfg["retry_interval"] = "1s"
        cfg.update(config_extra or {})
        self.config_path = os.path.join(self.tmp.name, "config.json")
        with open(self.config_path, "w") as f:
            json.dump(cfg, f)
        self.proc: Optional[subprocess.Popen] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "TestServer":
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # host plane must not dial TPU
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")  # XLA C++ log spew
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "consul_tpu.cli.main", "agent",
             "-config-file", self.config_path],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        self._drain = _Drain(self.proc.stdout)
        return self

    def stop(self) -> None:
        if self.proc is None:
            return
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(5)
        self.tmp.cleanup()

    def output(self) -> str:
        """Diagnostic dump (the drain thread owns the pipe; safe on a
        live process)."""
        return self._drain.text() if self.proc is not None else ""

    # -- readiness (testutil/wait.go WaitForResult/WaitForLeader) ------------

    def wait_for_api(self, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc is not None and self.proc.poll() is not None:
                raise RuntimeError(
                    f"agent {self.name} exited rc={self.proc.returncode}")
            try:
                self.http_get("/v1/agent/self")
                return
            except (urllib.error.URLError, ConnectionError, OSError):
                time.sleep(0.1)
        raise TimeoutError(f"agent {self.name} HTTP API never came up")

    def wait_for_leader(self, timeout: float = 30.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                leader = self.http_get("/v1/status/leader")
                if leader:
                    return leader
            except (urllib.error.URLError, ConnectionError, OSError):
                pass
            time.sleep(0.1)
        raise TimeoutError(f"agent {self.name} never saw a leader")

    # -- HTTP helpers (server.go HTTP seeding helpers) -----------------------

    def _url(self, path: str) -> str:
        return f"http://127.0.0.1:{self.ports['http']}{path}"

    def http_get(self, path: str) -> Any:
        with urllib.request.urlopen(self._url(path), timeout=10) as r:
            body = r.read()
        return json.loads(body) if body else None

    def http_put(self, path: str, data: Any = None) -> Any:
        if isinstance(data, (dict, list)):
            data = json.dumps(data).encode()
        req = urllib.request.Request(self._url(path), data=data or b"",
                                     method="PUT")
        with urllib.request.urlopen(req, timeout=10) as r:
            body = r.read()
        return json.loads(body) if body else None

    def http_delete(self, path: str) -> Any:
        req = urllib.request.Request(self._url(path), method="DELETE")
        with urllib.request.urlopen(req, timeout=10) as r:
            body = r.read()
        return json.loads(body) if body else None

    # -- DNS helper ----------------------------------------------------------

    def dns_query(self, name: str, qtype: int = 1) -> Dict[str, Any]:
        q = bytearray(struct.pack("!HHHHHH", 0x4242, 0x0100, 1, 0, 0, 0))
        for label in name.rstrip(".").split("."):
            q.append(len(label))
            q += label.encode()
        q.append(0)
        q += struct.pack("!HH", qtype, 1)
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.settimeout(5)
        s.sendto(bytes(q), ("127.0.0.1", self.ports["dns"]))
        buf, _ = s.recvfrom(4096)
        s.close()
        _, flags, _, an, _, ar = struct.unpack("!HHHHHH", buf[:12])
        return {"rcode": flags & 0xF, "ancount": an, "arcount": ar, "raw": buf}

    # -- CLI-against-IPC helper (the `consul members` path) ------------------

    def cli(self, *args: str, timeout: float = 15.0) -> subprocess.CompletedProcess:
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        return subprocess.run(
            [sys.executable, "-m", "consul_tpu.cli.main", *args,
             "-rpc-addr", f"127.0.0.1:{self.ports['rpc']}"],
            capture_output=True, text=True, timeout=timeout, env=env)

    @property
    def lan_addr(self) -> str:
        return f"127.0.0.1:{self.ports['serf_lan']}"


class TestPlane:
    """One forked TPU gossip plane daemon (``consul-tpu gossipd``): the
    rendezvous for ``gossip_backend=tpu`` black-box agents."""

    __test__ = False

    def __init__(self, gossip_interval: float = 0.05,
                 hb_lapse: float = 0.5, suspicion_mult: float = 2.0,
                 capacity: int = 64, slots: int = 32) -> None:
        self.port = _port_block()["http"]  # own block; any free port
        self.args = ["gossipd", "-bind", "127.0.0.1",
                     "-port", str(self.port),
                     "-capacity", str(capacity), "-slots", str(slots),
                     "-gossip-interval", str(gossip_interval),
                     "-hb-lapse", str(hb_lapse),
                     "-suspicion-mult", str(suspicion_mult)]
        self.proc: Optional[subprocess.Popen] = None

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self) -> "TestPlane":
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"   # forked plane runs the CPU kernel
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")  # XLA C++ log spew
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "consul_tpu.cli.main", *self.args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        self._drain = _Drain(self.proc.stdout)
        return self

    def wait_ready(self, timeout: float = 240.0) -> None:
        """Block until the plane accepts connections (the first kernel
        compile happens inside its start; the persistent cache makes
        restarts fast)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc is not None and self.proc.poll() is not None:
                raise RuntimeError(
                    f"gossipd exited rc={self.proc.returncode}:\n"
                    + self.output()[-2000:])
            try:
                s = socket.create_connection(("127.0.0.1", self.port),
                                             timeout=1.0)
                s.close()
                return
            except OSError:
                time.sleep(0.3)
        raise TimeoutError("gossip plane never came up:\n"
                           + self.output()[-2000:])

    def stop(self) -> None:
        if self.proc is None:
            return
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(5)

    def output(self) -> str:
        return self._drain.text() if self.proc is not None else ""
