"""Event dissemination + push/pull + multi-DC kernel tests
(BASELINE configs #3-#5 functional tier; statistical crossval lives in
test_gossip_crossval.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consul_tpu.gossip.events import (
    _SEEN, fire_events, init_events, run_event_rounds)
from consul_tpu.gossip.kernel import NEVER, init_state, run_rounds
from consul_tpu.gossip.multidc import (
    fire_in_dc, init_multidc, make_params, run_multidc_rounds)
from consul_tpu.gossip.params import SwimParams, lan_profile


def _alive(n):
    return jnp.ones((n,), bool)


class TestEventKernel:
    def test_single_event_full_coverage(self):
        p = lan_profile(512, pushpull_every=0)
        st = init_events(p, slots=8)
        st = fire_events(st, jnp.array([3], jnp.int32))
        key = jax.random.PRNGKey(0)
        st, cov = run_event_rounds(st, key, _alive(p.n), p, steps=30)
        # epidemic flooding: everyone saw it (cumulative count survives GC)
        assert int(st.n_seen[0]) == p.n
        # and it reached 50% live coverage well before the end
        half_round = int(np.argmax(np.asarray(cov[:, 0]) >= 0.5))
        assert 0 < half_round < 15

    def test_lamport_clocks_advance(self):
        p = lan_profile(64, pushpull_every=0)
        st = init_events(p, slots=4)
        st = fire_events(st, jnp.array([0], jnp.int32))
        assert int(st.ltime[0]) == 1
        assert int(st.node_ltime[0]) == 1
        key = jax.random.PRNGKey(1)
        st, _ = run_event_rounds(st, key, _alive(p.n), p, steps=20)
        # receivers witnessed the event: clock >= event ltime everywhere
        assert int(jnp.min(st.node_ltime)) >= 1
        # firing again uses a later lamport time
        st = fire_events(st, jnp.array([5], jnp.int32))
        idx = int(jnp.argmax(st.origin == 5))
        assert int(st.ltime[idx]) > 1

    def test_slot_gc_recycles(self):
        p = lan_profile(128, pushpull_every=0)
        st = init_events(st_slots := p, slots=2)
        st = fire_events(st, jnp.array([0, 1], jnp.int32))
        assert int(jnp.sum(st.slot_used)) == 2
        key = jax.random.PRNGKey(2)
        st, _ = run_event_rounds(st, key, _alive(p.n), p, steps=60)
        # after full spread + aging, slots are recycled
        assert int(jnp.sum(st.slot_used)) == 0
        # and can be reused
        st = fire_events(st, jnp.array([7], jnp.int32))
        assert int(jnp.sum(st.slot_used)) == 1

    def test_slot_overflow_counted(self):
        p = lan_profile(64, pushpull_every=0)
        st = init_events(p, slots=2)
        st = fire_events(st, jnp.array([0, 1, 2], jnp.int32))
        assert int(st.drops) == 1
        assert int(jnp.sum(st.slot_used)) == 2

    def test_dead_nodes_excluded(self):
        p = lan_profile(256, pushpull_every=0)
        st = init_events(p, slots=4)
        st = fire_events(st, jnp.array([10], jnp.int32))
        alive = _alive(p.n).at[:5].set(False)
        key = jax.random.PRNGKey(3)
        st, cov = run_event_rounds(st, key, alive, p, steps=30)
        # dead nodes never see it; every alive node did
        assert int(st.n_seen[0]) == p.n - 5
        assert float(np.asarray(cov[:, 0]).max()) == 1.0


class TestPushPull:
    @pytest.mark.slow
    def test_pushpull_recovers_lost_rumors(self):
        """Under heavy packet loss the budgeted flood stalls below full
        coverage; push/pull anti-entropy completes it (memberlist's
        documented reason for push/pull)."""
        n = 512
        base = dict(n=n, slots=8, loss_rate=0.0)
        # Events: simulate loss by tiny spread budget (retransmit starved)
        p_nopp = SwimParams(**base, retransmit_mult=0.35, pushpull_every=0)
        p_pp = SwimParams(**base, retransmit_mult=0.35, pushpull_every=10)
        key = jax.random.PRNGKey(4)
        covs = {}
        for name, p in (("nopp", p_nopp), ("pp", p_pp)):
            st = init_events(p, slots=4)
            st = fire_events(st, jnp.array([0], jnp.int32))
            st, cov = run_event_rounds(st, key, _alive(n), p, steps=80)
            covs[name] = int(st.n_seen[0]) / n
        assert covs["pp"] == 1.0
        assert covs["nopp"] < covs["pp"]

    def test_pushpull_membership_merge(self):
        """The dead verdict reaches everyone even when the spread budget
        is starved, thanks to the belief exchange."""
        n = 256
        p = SwimParams(n=n, slots=8, retransmit_mult=0.3, pushpull_every=8)
        st = init_state(p)
        fail = jnp.full((n,), NEVER, jnp.int32).at[9].set(5)
        key = jax.random.PRNGKey(5)
        st, _ = run_rounds(st, key, fail, p, steps=400)
        assert int(st.n_detected) == 1
        assert not bool(st.member[9])


class TestMultiDC:
    def test_event_crosses_datacenters(self):
        p = make_params(n_dcs=3, n_lan=128, n_servers=3, event_slots=4)
        st = init_multidc(p)
        st = fire_in_dc(st, dc=0, node=50, p=p)
        lan_fail = jnp.full((p.n_dcs, p.n_lan), NEVER, jnp.int32)
        wan_fail = jnp.full((p.n_dcs * p.n_servers,), NEVER, jnp.int32)
        key = jax.random.PRNGKey(6)
        st, cov = run_multidc_rounds(st, key, lan_fail, wan_fail, p, steps=60)
        peak = np.asarray(cov).max(axis=0)  # [D, E] best live coverage
        # the event covered every DC, not just its origin
        assert (peak[:, 0] == 1.0).all(), peak[:, 0]
        # origin DC converged no later than remote DCs
        origin_half = int(np.argmax(np.asarray(cov[:, 0, 0]) >= 0.5))
        remote_half = int(np.argmax(np.asarray(cov[:, 1, 0]) >= 0.5))
        assert origin_half <= remote_half

    def test_lan_failure_detected_per_dc(self):
        p = make_params(n_dcs=2, n_lan=128, n_servers=3, event_slots=2)
        st = init_multidc(p)
        lan_fail = jnp.full((2, 128), NEVER, jnp.int32).at[1, 60].set(10)
        wan_fail = jnp.full((6,), NEVER, jnp.int32)
        key = jax.random.PRNGKey(7)
        st, _ = run_multidc_rounds(st, key, lan_fail, wan_fail, p, steps=400)
        # DC1 detected its dead node; DC0 membership untouched
        assert int(st.lan.n_detected[1]) == 1
        assert not bool(st.lan.member[1, 60])
        assert int(st.lan.n_detected[0]) == 0
        assert bool(st.lan.member[0].all())

    def test_wan_server_failure_detected(self):
        p = make_params(n_dcs=3, n_lan=64, n_servers=3, event_slots=2)
        st = init_multidc(p)
        lan_fail = jnp.full((3, 64), NEVER, jnp.int32)
        wan_fail = jnp.full((9,), NEVER, jnp.int32).at[4].set(20)
        key = jax.random.PRNGKey(8)
        st, _ = run_multidc_rounds(st, key, lan_fail, wan_fail, p, steps=800)
        assert int(st.wan.n_detected) == 1
        assert not bool(st.wan.member[4])
