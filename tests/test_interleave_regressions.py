"""Regression tests for the interleaving races the X/T vet passes
surfaced (tools/vet/interleave.py, tools/vet/role_transition.py).

Each test pins one production fix:

- anti-entropy lost update (agent/local.py sync_changes): a service or
  check mutated while its register RPC is in flight must stay marked
  out-of-sync, or the newer definition silently waits a full ae_scale
  interval.
- deposed-leader-never-serves (consensus/raft.py): both transition
  helpers must drop ``_lease_ack`` so a lease_valid() caller scheduled
  between the role flip and ``_stop_leading`` cannot count a dead
  quorum as fresh.
- swap-then-act teardown (agent/workers.py, tools/bench_serve.py):
  two concurrent close() calls suspended at the same await must not
  both act on the one shared handle.

The mutations here are injected synchronously from inside the awaited
stub — the exact schedule the forced-interleave dyn leg
(CONSUL_TPU_DYN_INTERLEAVE=1) produces at every await point, made
deterministic.
"""

from __future__ import annotations

import asyncio

from consul_tpu.agent.local import LocalState
from consul_tpu.agent.workers import WorkerFront
from consul_tpu.consensus.raft import (
    CANDIDATE, FOLLOWER, MemoryTransport, RaftNode)
from consul_tpu.structs.structs import HealthCheck, NodeService

from tools.bench_serve import KeepAliveConn


class StubCatalogAgent:
    """The minimal agent surface LocalState syncs against, with an
    injection hook that fires inside the register await — i.e. while
    sync_changes() is suspended."""

    node_name = "n1"
    advertise_addr = "127.0.0.1"

    def __init__(self):
        self.registered = []
        self.on_register = None

    def cluster_size(self):
        return 1

    async def catalog_node_services(self, node):
        return {}

    async def catalog_node_checks(self, node):
        return []

    async def catalog_deregister(self, req):
        pass

    async def catalog_register(self, req):
        self.registered.append(req)
        if self.on_register is not None:
            hook, self.on_register = self.on_register, None
            hook()


# -- anti-entropy lost update (agent/local.py) -------------------------------


def test_service_replaced_mid_register_stays_out_of_sync():
    async def run():
        agent = StubCatalogAgent()
        ls = LocalState(agent)
        ls.add_service(NodeService(id="web", service="web", port=80))
        newer = NodeService(id="web", service="web", port=81)
        agent.on_register = lambda: ls.add_service(newer)

        await ls.sync_changes()
        # The pass pushed port 80; the port-81 definition landed during
        # the await and must NOT be marked synced by it.
        assert ls._service_sync["web"] is False
        assert ls.pending_ops() == 1

        await ls.sync_changes()
        assert ls._service_sync["web"] is True
        assert agent.registered[-1].service.port == 81

    asyncio.run(run())


def test_check_flip_mid_register_stays_out_of_sync():
    async def run():
        agent = StubCatalogAgent()
        ls = LocalState(agent)
        ls.add_check(HealthCheck(check_id="c1", name="ping",
                                 status="passing"))
        # update_check mutates the check IN PLACE, so an identity test
        # alone cannot catch this — the (status, output) pair must.
        agent.on_register = lambda: ls.update_check("c1", "critical",
                                                    "conn refused")

        await ls.sync_changes()
        assert ls._check_sync["c1"] is False

        await ls.sync_changes()
        assert ls._check_sync["c1"] is True
        assert agent.registered[-1].check.status == "critical"

    asyncio.run(run())


def test_unchanged_entries_marked_synced_in_one_pass():
    # The guard must not over-fire: with no concurrent mutation a
    # single pass converges.
    async def run():
        agent = StubCatalogAgent()
        ls = LocalState(agent)
        ls.add_service(NodeService(id="web", service="web", port=80))
        ls.add_check(HealthCheck(check_id="c1", name="ping",
                                 status="passing"))
        await ls.sync_changes()
        assert ls._service_sync["web"] is True
        assert ls._check_sync["c1"] is True
        assert ls.pending_ops() == 0

    asyncio.run(run())


# -- deposed-leader-never-serves (consensus/raft.py) -------------------------


def _node(peers=("s0", "s1", "s2")):
    return RaftNode("s0", list(peers), fsm=None,
                    transport=MemoryTransport())


def test_become_candidate_drops_stale_lease():
    async def run():
        node = _node()
        node._lease_ack = {"s1": 123.0, "s2": 124.0}
        node._become_candidate()
        assert node._lease_ack == {}
        assert node.role == CANDIDATE
        assert node.current_term == 1
        assert node.voted_for == "s0"
        # the vote must survive a restart (Raft §5.1)
        assert node.log.get_stable("term", 0) == 1
        assert node.log.get_stable("voted_for", None) == "s0"

    asyncio.run(run())


def test_become_follower_drops_lease_before_stop_leading():
    async def run():
        node = _node()
        node._lease_ack = {"s1": 123.0, "s2": 124.0}
        node._become_follower(5, "s1")
        # cleared HERE, not a scheduling turn later in _stop_leading —
        # a lease check interleaved between the two must see nothing.
        assert node._lease_ack == {}
        assert node.role == FOLLOWER
        assert node.current_term == 5
        assert node.leader_id == "s1"

    asyncio.run(run())


# -- swap-then-act teardown --------------------------------------------------


class _CountingWriter:
    def __init__(self):
        self.closed = 0

    def close(self):
        self.closed += 1

    async def wait_closed(self):
        await asyncio.sleep(0)   # a real suspension point


class _CountingSession:
    def __init__(self):
        self.closed = 0

    async def close(self):
        self.closed += 1
        await asyncio.sleep(0)


class _NullGateway:
    async def close(self):
        await asyncio.sleep(0)


def test_bench_conn_concurrent_close_closes_once():
    async def run():
        conn = KeepAliveConn("127.0.0.1", 1)
        writer = _CountingWriter()
        conn.writer = writer
        await asyncio.gather(conn.close(), conn.close())
        assert writer.closed == 1
        assert conn.writer is None

    asyncio.run(run())


def test_worker_front_concurrent_close_closes_session_once():
    async def run():
        front = object.__new__(WorkerFront)   # skip the network setup
        front.gw = _NullGateway()
        front._session = _CountingSession()
        session = front._session
        await asyncio.gather(front.close(), front.close())
        assert session.closed == 1
        assert front._session is None

    asyncio.run(run())
