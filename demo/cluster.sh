#!/usr/bin/env bash
# Local demo cluster — the demo/vagrant-cluster role of the reference,
# without VMs: three server agents + one client agent on loopback with
# distinct port blocks, formed via bootstrap_expect + retry_join.
#
#   ./demo/cluster.sh up      # start 4 agents (data under /tmp/consul-tpu-demo)
#   ./demo/cluster.sh up-tpu  # same cluster, membership on the TPU gossip
#                             # plane (gossip_backend=tpu + gossipd daemon)
#   ./demo/cluster.sh status  # members + leader via agent 1
#   ./demo/cluster.sh demo    # seed a service + KV, query HTTP/DNS
#   ./demo/cluster.sh down    # stop everything
set -euo pipefail
cd "$(dirname "$0")/.."

ROOT=/tmp/consul-tpu-demo
BASE=23000

PLANE_PORT=$((BASE + 99))

cfg() { # name idx server expect [gossip_extra]
  local name=$1 idx=$2 server=$3 expect=$4 gossip_extra=${5:-}
  local base=$((BASE + idx * 10))
  mkdir -p "$ROOT/$name"
  cat > "$ROOT/$name/config.json" <<EOF
{
  "node_name": "$name",
  "server": $server,
  "bootstrap": false,
  "bootstrap_expect": $expect,
  "bind_addr": "127.0.0.1",
  "client_addr": "127.0.0.1",
  "data_dir": "$ROOT/$name/data",
  "retry_join": ["127.0.0.1:$((BASE + 3))"],
  "retry_interval": "1s",
  "log_level": "WARN",$gossip_extra
  "ports": {"http": $base, "dns": $((base + 1)), "rpc": $((base + 2)),
            "serf_lan": $((base + 3)), "serf_wan": $((base + 4)),
            "server": $((base + 5))}
}
EOF
}

up() {
  local gossip_extra=""
  rm -rf "$ROOT"; mkdir -p "$ROOT"
  # GOSSIP_KEY=<base64 16-byte key> arms gossip encryption: agents get
  # the serf keyring AND (with up-tpu) the plane requires keyring HMAC
  # registration proofs — the encrypted-fabric posture on both
  # substrates.  e.g. GOSSIP_KEY=$(head -c16 /dev/urandom | base64)
  local encrypt_extra="" plane_encrypt=()
  if [ -n "${GOSSIP_KEY:-}" ]; then
    encrypt_extra='
  "encrypt": "'$GOSSIP_KEY'",'
    plane_encrypt=(-encrypt "$GOSSIP_KEY")
  fi
  if [ "${1:-}" = tpu ]; then
    # Membership substrate = the SWIM kernel in the gossipd daemon:
    # suspicion/Lifeguard/refutation/dead verdicts run on-device, and
    # the agents' serf boundary consumes the verdicts.
    gossip_extra='
  "gossip_backend": "tpu",
  "gossip_plane": "127.0.0.1:'$PLANE_PORT'",'
    # GOSSIPD_JAX_PLATFORMS=axon (plus the axon PYTHONPATH) runs the
    # plane on the real chip; the demo defaults to the CPU kernel.
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS="${GOSSIPD_JAX_PLATFORMS:-cpu}" \
      python -m consul_tpu.cli.main gossipd -port $PLANE_PORT \
      "${plane_encrypt[@]}" \
      > "$ROOT/gossipd.log" 2>&1 &
    echo $! > "$ROOT/gossipd.pid"
    echo "started gossipd (pid $(cat "$ROOT/gossipd.pid"), port $PLANE_PORT)"
    echo "waiting for the plane (first kernel compile can take ~30s)..."
    for _ in $(seq 240); do
      kill -0 "$(cat "$ROOT/gossipd.pid")" 2>/dev/null || {
        echo "gossipd died:"; tail -5 "$ROOT/gossipd.log"; exit 1; }
      (echo > /dev/tcp/127.0.0.1/$PLANE_PORT) 2>/dev/null && break
      sleep 1
    done
    (echo > /dev/tcp/127.0.0.1/$PLANE_PORT) 2>/dev/null || {
      echo "gossip plane never came up:"; tail -5 "$ROOT/gossipd.log"; exit 1; }
  fi
  cfg s1 0 true 3 "$gossip_extra$encrypt_extra"
  cfg s2 1 true 3 "$gossip_extra$encrypt_extra"
  cfg s3 2 true 3 "$gossip_extra$encrypt_extra"
  cfg c1 3 false 0 "$gossip_extra$encrypt_extra"
  for n in s1 s2 s3 c1; do
    env -u PALLAS_AXON_POOL_IPS python -m consul_tpu.cli.main agent \
      -config-file "$ROOT/$n/config.json" > "$ROOT/$n/log" 2>&1 &
    echo $! > "$ROOT/$n/pid"
    echo "started $n (pid $(cat "$ROOT/$n/pid"))"
  done
  echo "waiting for leader..."
  for _ in $(seq 60); do
    leader=$(curl -sf "127.0.0.1:$BASE/v1/status/leader" 2>/dev/null || true)
    [ -n "${leader:-}" ] && [ "$leader" != '""' ] && break
    sleep 0.5
  done
  echo "leader: ${leader:-none}"
  echo "HTTP: 127.0.0.1:$BASE   UI: http://127.0.0.1:$BASE/ui/   DNS: 127.0.0.1:$((BASE + 1))"
}

status() {
  env -u PALLAS_AXON_POOL_IPS python -m consul_tpu.cli.main members \
    -rpc-addr "127.0.0.1:$((BASE + 2))"
  echo "leader: $(curl -s "127.0.0.1:$BASE/v1/status/leader")"
}

demo() {
  c1http=$((BASE + 30))
  echo "== register service 'web' on the CLIENT agent =="
  curl -s -X PUT "127.0.0.1:$c1http/v1/agent/service/register" \
       -d '{"Name": "web", "Port": 8080, "Tags": ["demo"]}'
  echo "== write KV through the client =="
  curl -s -X PUT "127.0.0.1:$c1http/v1/kv/demo/greeting" -d 'hello from c1'
  echo; sleep 2
  echo "== service catalog (via server s2) =="
  curl -s "127.0.0.1:$((BASE + 10))/v1/catalog/service/web"; echo
  echo "== KV read (via server s3) =="
  curl -s "127.0.0.1:$((BASE + 20))/v1/kv/demo/greeting?raw"; echo
  echo "== DNS SRV via the client agent =="
  command -v dig >/dev/null && \
    dig +short @127.0.0.1 -p $((c1http + 1)) web.service.consul SRV || \
    echo "(dig not installed; try: dig @127.0.0.1 -p $((c1http + 1)) web.service.consul SRV)"
}

down() {
  for n in s1 s2 s3 c1; do
    [ -f "$ROOT/$n/pid" ] && kill "$(cat "$ROOT/$n/pid")" 2>/dev/null || true
  done
  [ -f "$ROOT/gossipd.pid" ] && kill "$(cat "$ROOT/gossipd.pid")" 2>/dev/null || true
  echo "stopped"
}

case "${1:-}" in
  up) up ;;
  up-tpu) up tpu ;;
  status) status ;;
  demo) demo ;;
  down) down ;;
  *) echo "usage: $0 up|status|demo|down"; exit 1 ;;
esac
