"""North-star benchmark: SWIM gossip rounds/sec at 1M simulated nodes.

Target from BASELINE.json config #5: >=10k gossip rounds/sec at 1M nodes
(reference substrate: memberlist's event-driven gossip, which the TPU
kernel re-designs as batched synchronous rounds — see
consul_tpu/gossip/kernel.py).  vs_baseline is measured rounds/sec over
that 10k/s target.

Prints exactly ONE JSON line on stdout.  The default invocation (no
args) measures the full **regime table** — healthy cluster (churn 0),
0.1%-churn stress, and the BASELINE config-#5 multi-DC shape — in one
backend session, and the payload carries all three plus compile times
and the dense-regime roofline estimate:

    {"metric": ..., "value": N, "unit": "rounds/s", "vs_baseline": N,
     "regimes": {"healthy": {...}, "churn1000ppm": {...},
                 "churn1000ppm_planes": {...},
                 "realistic_churn10ppm": {...},
                 "realistic_churn10ppm_hot8": {...}, "multidc": {...}},
     "roofline_rounds_per_sec": N, ...}

A/Bs ride the table so pending lowering decisions are settled by
whatever capture next reaches a chip: churn1000ppm vs _planes vs
_prefused is the dissemination-strategy A/B (params.dissem; _prefused
also rides the healthy regime), and realistic_churn10ppm vs _hot8 is
the hot-tier decision (params.hot_slots) in the 1-2-live-episode
regime the tier exists for.

The headline metric/value is the historical churn1000ppm stress regime
(cross-round comparability); the healthy row is the operating point
for BASELINE's scale posture — see BENCH_NOTES.md §1c for the
churn-rate calibration.  Flags (--multidc / --churn-ppm / --n /
--hot-slots / --dissem) still run a single regime for manual
profiling sessions.

All progress/diagnostics go to stderr.  Resilience (round-1 failure was
an unretried backend-init crash with no JSON at all; round-3 failure was
a tunnel hang that starved the whole capture):
  * backend liveness is probed out-of-process with several SHORT
    timeouts + backoff rather than two long ones;
  * a persistent compilation cache (.jax_cache/) amortizes the 1M-node
    compile across invocations;
  * compile time is measured and reported separately from steady state;
  * if a full-size run fails (init/OOM/compile), that regime backs off
    to n/4 repeatedly and reports the largest size that ran;
  * each regime's result is cached the moment it is measured, so a
    wedge mid-table still leaves the earlier regimes' live numbers;
  * any terminal failure still emits a parseable JSON line with an
    "error" field, with the cache fallback matched to the exact regime
    (variant + churn suffix) that failed;
  * every regime carries a "phases" event timeline (probe attempts in
    the payload-level "boot_phases", then compile/measure blocks and
    salvage decisions with durations and outcomes) — written for
    successful runs too, so BENCH_r06+ have trend data and the next
    tunnel hang is a readable event log instead of a zero.  A single
    table row reruns by name via --regime (e.g. --regime healthy).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

TARGET_ROUNDS_PER_SEC = 10_000.0
MIN_FALLBACK_N = 65_536

# Dense-regime roofline (BENCH_NOTES.md §1c) — single source of truth
# in obs/devstats.py (no jax import there, so safe pre-probe); bench,
# tools/profile_kernel.py, and the live agent all report the same
# derivation, closing the loop between bench numbers and the serving
# plane.
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from consul_tpu.obs.devstats import (  # noqa: E402
    DENSE_PASSES_PER_ROUND, EFFECTIVE_HBM_GBPS, dense_bytes_per_round,
    roofline_utilization)


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


class _Timeline:
    """Per-regime phase event log (the post-hoc diagnosis the BENCH_r04/
    r05 zeros never had): every probe attempt, compile, timed block, and
    salvage decision lands here with a wall-clock offset and outcome,
    and the list is persisted into the JSON payload for successful AND
    wedged regimes alike."""

    def __init__(self) -> None:
        self._t0 = time.monotonic()
        self.events: list[dict] = []

    def note(self, phase: str, outcome: str = "ok",
             dur_s: float | None = None, **detail) -> None:
        ev = {"phase": phase,
              "t_s": round(time.monotonic() - self._t0, 3),
              "outcome": outcome}
        if dur_s is not None:
            ev["dur_s"] = round(dur_s, 3)
        ev.update(detail)
        self.events.append(ev)


# Process-lifetime timeline: backend probe attempts + backend-up/gave-up
# verdicts, emitted as "boot_phases" alongside every payload shape.
_BOOT = _Timeline()


def _want_cpu() -> bool:
    return os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip() == "cpu"


def _probe_backend(timeout_s: float) -> tuple[bool, str]:
    """Initialize the jax backend in a THROWAWAY subprocess with a hard
    timeout.  Backend init dials the TPU tunnel and can hang
    indefinitely inside a C call (uninterruptible in-process — the
    round-1 failure shape), so the liveness check must be a process we
    can kill.

    When JAX_PLATFORMS=cpu is requested (smoke runs), the axon
    interpreter-start hook must be disarmed in the child too — it pins
    jax_platforms and dials the tunnel regardless of the env var."""
    import subprocess

    env = dict(os.environ)
    code = "import jax; d = jax.devices(); print(d[0].platform, len(d))"
    if _want_cpu():
        env.pop("PALLAS_AXON_POOL_IPS", None)
        code = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
                "d = jax.devices(); print(d[0].platform, len(d))")
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired:
        return False, f"backend init exceeded {timeout_s:.0f}s (tunnel hang?)"
    if r.returncode == 0:
        return True, r.stdout.strip()
    tail = (r.stderr or "").strip().splitlines()
    return False, "; ".join(tail[-3:]) if tail else f"rc={r.returncode}"


def _setup_jax(retries: int = 6, probe_timeout_s: float = 40.0):
    """Probe backend liveness out-of-process, then init in-process with
    the persistent compile cache enabled.

    Many SHORT probes with exponential backoff, not a few long ones:
    the round-3 capture lost its whole window to 2×240s hangs.  A
    healthy backend answers the probe in ~10-20s, so 40s already has
    2x headroom — a probe that silent past that is wedged, not slow.
    The pause doubles (4s -> 64s cap) because a stuck tunnel-grant
    clears when its holder dies, on a timescale of tens of seconds:
    early retries catch a fast recovery, the growing pause stops the
    probes themselves from burning the window when it is a slow one."""
    last = "unknown"
    for attempt in range(1, retries + 1):
        t0 = time.perf_counter()
        ok, info = _probe_backend(probe_timeout_s)
        dt = time.perf_counter() - t0
        if ok:
            _log(f"backend probe ok: {info}")
            _BOOT.note("backend_probe", dur_s=dt, attempt=attempt,
                       info=info)
            break
        last = info
        _log(f"backend probe failed (attempt {attempt}/{retries}): {info}")
        _BOOT.note("backend_probe", outcome="fail", dur_s=dt,
                   attempt=attempt, info=info)
        if attempt < retries:
            time.sleep(min(4.0 * 2 ** (attempt - 1), 64.0))
    else:
        _BOOT.note("backend_up", outcome="gave_up", attempts=retries,
                   info=last)
        raise RuntimeError(f"jax backend unreachable after {retries} probes: {last}")

    if _want_cpu():
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax

    if _want_cpu():
        jax.config.update("jax_platforms", "cpu")
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # cache flags are best-effort across jax versions
        _log(f"compilation cache unavailable: {e}")

    devs = jax.devices()
    global _PLATFORM
    _PLATFORM = devs[0].platform
    _log(f"backend up: {len(devs)}x {devs[0].platform} "
         f"({getattr(devs[0], 'device_kind', '?')})")
    _BOOT.note("backend_up", platform=devs[0].platform, devices=len(devs))
    return jax


def _sync(jax, state) -> None:
    """Wait for the step to FINISH, not merely be enqueued.  On the
    tunneled axon backend block_until_ready can return once the handle
    is committed rather than executed (observed: 2.8M rounds/s, ~1000x
    the HBM roofline — physically impossible); a device->host scalar
    fetch cannot lie about completion."""
    jax.block_until_ready(state)
    int(state.round if hasattr(state, "round") else jax.tree.leaves(state)[0])


def _bench_lan(jax, n: int, slots: int, steps: int, repeats: int,
               churn_ppm: int = 1000, dissem: str = "swar",
               hot_slots: int = 0, flight: bool = False,
               shard_devices: int = 0, nemesis: str = "",
               tl: _Timeline | None = None) -> dict:
    import functools

    import jax.numpy as jnp

    from consul_tpu.gossip.kernel import (
        init_flight, init_state, run_rounds, run_rounds_sharded, shard_state)
    from consul_tpu.gossip.params import lan_profile

    p = lan_profile(n, slots=slots, dissem=dissem,
                    hot_slots=hot_slots)
    state = init_state(p)
    # shard_devices > 0: the shard_map'd kernel over that many local
    # devices (kernel.py "ICI sharding"; raises unless n is divisible
    # by shard_devices and probe_every).  1 measures the shard_map
    # wrapping overhead itself; the scaling curve is the regime table's
    # _shard{d} entries.
    if shard_devices:
        state = shard_state(state, shard_devices)
        run = functools.partial(run_rounds_sharded, p=p,
                                ndev=shard_devices)
    else:
        run = functools.partial(run_rounds, p=p)
    # Flight-recorder overhead regime: the on-device ring rides the
    # scan carry exactly as the gossip plane runs it; the ring is NOT
    # drained inside timed blocks (the plane amortizes drains over
    # >= 64 rounds, off the hot path), so the measured delta is the
    # pure in-kernel recording cost.
    fl = init_flight() if flight else None
    key = jax.random.PRNGKey(42)
    # Steady-state failure churn (default 0.1% of nodes, staggered over
    # warmup AND every timed block, so probe/suspect/dead/GC paths stay
    # hot in whichever block min() selects).  --churn-ppm 0 benches the
    # healthy-cluster regime: no episodes, rounds take the quiescent
    # fast path (probe tick only).
    n_fail = (n * churn_ppm) // 1_000_000 if churn_ppm else 0
    if churn_ppm and n_fail == 0:
        n_fail = 1
    total_rounds = steps * (repeats + 1)
    # Stride, not modulo: failures land uniformly across every block even
    # when n_fail < total_rounds.
    fail_round = jnp.full((p.n,), 2**31 - 1, jnp.int32)
    if n_fail:
        # Stride, not modulo: failures land uniformly across every block.
        fail_round = fail_round.at[:n_fail].set(
            (jnp.arange(n_fail, dtype=jnp.int32) * total_rounds) // n_fail)

    # Nemesis regime (gossip/nemesis.py): the scenario's injection
    # schedule — partition/loss masks, flapping rejoin, the Lifeguard
    # LHM carry — rides the TIMED blocks, so the regime A/Bs the
    # fault-injection overhead against its churn baseline.  The window
    # is widened to the whole run: the catalog windows are oracle-scale
    # and would elapse inside warmup here, leaving the masks compiled
    # in but the fault dormant.
    nem = nem_join = ns = None
    if nemesis:
        import dataclasses

        from consul_tpu.gossip.kernel import init_nem_state
        from consul_tpu.gossip.nemesis import build as build_nemesis
        sc = build_nemesis(nemesis, n)
        nem = dataclasses.replace(sc.nem, start=0, stop=2**31 - 1)
        fail_round = jnp.minimum(fail_round, jnp.asarray(sc.fail_round))
        if nem.needs_join:
            nem_join = (jnp.asarray(sc.join_round)
                        if sc.join_round is not None
                        else jnp.full((p.n,), 2**31 - 1, jnp.int32))
        if nem.needs_state:
            ns = init_nem_state(p.n)

    def _dispatch(state, fail, fl=None, ns=None, hist=None):
        """One run_rounds call with whatever extras this regime
        threads; unpacks the carry in its fixed
        (state[, flight][, hist][, nem_state]) order."""
        kw = {}
        if fl is not None:
            kw["flight"] = fl
        if hist is not None:
            kw["hist"] = hist
        if nem is not None:
            kw["nem"] = nem
            if nem_join is not None:
                kw["join_round"] = nem_join
            if ns is not None:
                kw["nem_state"] = ns
        out, _ = run(state, key, fail, steps=steps, **kw)
        parts = (out,) if hasattr(out, "member") else tuple(out)
        state, i = parts[0], 1
        if fl is not None:
            fl, i = parts[i], i + 1
        if hist is not None:
            hist, i = parts[i], i + 1
        if ns is not None:
            ns = parts[i]
        return state, fl, ns, hist

    tl = tl or _Timeline()
    _log(f"lan n={n} slots={slots}: compiling + warmup ({steps} rounds)")
    t0 = time.perf_counter()
    state, fl, ns, _ = _dispatch(state, fail_round, fl, ns)
    _sync(jax, state)
    compile_s = time.perf_counter() - t0
    _log(f"compile+warmup done in {compile_s:.1f}s")
    tl.note("compile_warmup", dur_s=compile_s, n=n, rounds=steps)

    best = float("inf")
    for r in range(repeats):
        t0 = time.perf_counter()
        state, fl, ns, _ = _dispatch(state, fail_round, fl, ns)
        _sync(jax, state)
        dt = time.perf_counter() - t0
        best = min(best, dt)
        _log(f"block {r + 1}/{repeats}: {steps / dt:.1f} rounds/s")
        tl.note("measure", dur_s=dt, block=r + 1,
                rounds_per_sec=round(steps / dt, 1))

    rps = steps / best
    result = {
        "metric": (f"swim_gossip_rounds_per_sec_{n}_nodes"
                   + ("" if churn_ppm == 1000 else f"_churn{churn_ppm}ppm")
                   + (f"_hot{hot_slots}" if hot_slots else "")
                   + ("" if dissem == "swar" else f"_{dissem}")
                   + ("_flight" if flight else "")
                   + (f"_shard{shard_devices}" if shard_devices else "")
                   + (f"_nem_{nemesis}" if nemesis else "")),
        "value": round(rps, 1),
        "unit": "rounds/s",
        "vs_baseline": round(rps / TARGET_ROUNDS_PER_SEC, 3),
        "compile_s": round(compile_s, 1),
        "n_nodes": n,
        "dissem": dissem,
        "hot_slots": hot_slots,
        "shard_devices": shard_devices,
    }
    # The same roofline-utilization figure the live agent exports
    # (consul_kernel_roofline_utilization — one derivation, devstats):
    # achieved HBM traffic over the §1c ceiling.  Quiescent regimes can
    # exceed 1.0 — they skip the dense passes the estimate assumes.
    util = roofline_utilization(dense_bytes_per_round(slots, n, dissem),
                                rps)
    if util is not None:
        result["roofline_utilization"] = round(util, 6)
    if flight:
        # One drain AFTER timing: proves rows were recorded without a
        # host transfer inside the measured blocks.
        result["flight_rows_recorded"] = int(fl.cursor)
    if nemesis:
        result["nemesis"] = nemesis
    if churn_ppm or nemesis:
        # Detection-latency observatory (untimed): one extra block on a
        # fresh state with the in-kernel histogram banks threaded
        # through, failures confined to the first half so verdicts have
        # room to land.  Separate from the timed blocks — the headline
        # rounds/s and compile_s stay what they always measured.
        import numpy as np

        from consul_tpu.gossip.kernel import init_hist, init_nem_state
        from consul_tpu.obs.hist import HistRecorder
        _log("observatory block: detection-latency histograms (untimed)")
        t_obs = time.perf_counter()
        h_state = init_state(p)
        if shard_devices:
            h_state = shard_state(h_state, shard_devices)
        h_fail = fail_round.at[:n_fail].set(
            (jnp.arange(n_fail, dtype=jnp.int32) * (steps // 2))
            // max(1, n_fail)) if n_fail else fail_round
        h_ns = (init_nem_state(p.n)
                if nem is not None and nem.needs_state else None)
        h_state, _, _, hist = _dispatch(h_state, h_fail, None, h_ns,
                                        init_hist())
        _sync(jax, h_state)
        rec = HistRecorder()
        deltas = rec.ingest({f: np.asarray(getattr(hist, f))
                             for f in hist._fields},
                            scenario=nemesis or None)
        result["detect_count"] = int(rec.counts("detect").sum())
        result["detect_p50_rounds"] = rec.percentile("detect", 50)
        result["detect_p99_rounds"] = rec.percentile("detect", 99)
        tl.note("observatory", dur_s=time.perf_counter() - t_obs,
                detections=result["detect_count"])
        if nemesis:
            # Per-scenario SLO readout (BENCH_NOTES §8): same objective
            # the live plane serves at /v1/agent/slo.
            from consul_tpu.obs.slo import SloTracker
            tr = SloTracker(p.suspicion_max_rounds + p.probe_every)
            tr.observe(deltas["detect"])
            snap = tr.snapshot()
            result["slo"] = {k: snap[k] for k in
                             ("objective_rounds", "detections",
                              "attainment", "burn_rate")}
    return result


def _bench_multidc(jax, n: int, dcs: int, slots: int, steps: int,
                   repeats: int, tl: _Timeline | None = None) -> dict:
    """Config #5 shape: D LAN pools + WAN pool + cross-DC event propagation."""
    import jax.numpy as jnp

    from consul_tpu.gossip.kernel import NEVER
    from consul_tpu.gossip.multidc import (
        fire_in_dc, init_multidc, make_params, run_multidc_rounds)

    n_lan = n // dcs
    p = make_params(n_dcs=dcs, n_lan=n_lan, n_servers=3,
                    event_slots=32, slots=slots)
    state = init_multidc(p)
    state = fire_in_dc(state, dc=0, node=7, p=p)
    key = jax.random.PRNGKey(42)
    n_fail = max(1, n_lan // 1000)
    total_rounds = steps * (repeats + 1)
    per_dc = (jnp.arange(n_fail, dtype=jnp.int32) * total_rounds) // n_fail
    # Offset past the server ids: killing the bridge nodes would bench a
    # topology with no live LAN<->WAN relay.
    s0 = p.n_servers
    lan_fail = (jnp.full((p.n_dcs, n_lan), NEVER, jnp.int32)
                .at[:, s0:s0 + n_fail].set(per_dc[None, :]))
    wan_fail = jnp.full((p.n_dcs * p.n_servers,), NEVER, jnp.int32)

    tl = tl or _Timeline()
    _log(f"multidc n={n} dcs={dcs}: compiling + warmup ({steps} rounds)")
    t0 = time.perf_counter()
    state, _ = run_multidc_rounds(state, key, lan_fail, wan_fail, p,
                                  steps=steps)
    _sync(jax, state.wan)
    compile_s = time.perf_counter() - t0
    _log(f"compile+warmup done in {compile_s:.1f}s")
    tl.note("compile_warmup", dur_s=compile_s, n=n, rounds=steps)

    best = float("inf")
    for r in range(repeats):
        t0 = time.perf_counter()
        state, _ = run_multidc_rounds(state, key, lan_fail, wan_fail, p,
                                      steps=steps)
        _sync(jax, state.wan)
        dt = time.perf_counter() - t0
        best = min(best, dt)
        _log(f"block {r + 1}/{repeats}: {steps / dt:.1f} rounds/s")
        tl.note("measure", dur_s=dt, block=r + 1,
                rounds_per_sec=round(steps / dt, 1))

    rps = steps / best
    return {
        "metric": f"swim_multidc_rounds_per_sec_{n}_nodes_{dcs}dc",
        "value": round(rps, 1),
        "unit": "rounds/s",
        "vs_baseline": round(rps / TARGET_ROUNDS_PER_SEC, 3),
        "compile_s": round(compile_s, 1),
        "n_nodes": n,
    }


_LAST_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".bench_last_success.json")

# Metric-name shape: swim_{gossip|multidc}_rounds_per_sec_{n}_nodes
# [+ "_churn{ppm}ppm" for non-default churn | "_{d}dc" for multidc]
# [+ "_planes"/"_prefused"/"_fused" for a non-default dissemination
#    strategy (params.dissem; swar has no suffix historically)]
# [+ "_flight" with the kernel flight recorder enabled]
# [+ "_shard{d}" for the shard_map'd kernel over d devices]
# [+ "_nem_{scenario}" with a nemesis injection schedule active].
_METRIC_RE = re.compile(
    r"^swim_(gossip|multidc)_rounds_per_sec_(\d+)_nodes"
    r"(?:_churn(\d+)ppm)?(?:_(\d+)dc)?(?:_hot(\d+))?"
    r"(_planes|_prefused|_fused)?(_flight)?"
    r"(?:_shard(\d+))?(?:_nem_([a-z0-9_]+))?$")


def _regime_key(multidc: bool, churn_ppm: int,
                dissem: str = "swar", hot: int = 0,
                flight: bool = False, shard: int = 0,
                nemesis: str = "") -> tuple:
    """Cache-matching key: bench variant + churn regime + dissemination
    strategy + device count + nemesis scenario, size-agnostic.  The
    default LAN run (churn 1000 ppm) has NO suffix historically, so the
    regime must be recovered from the parsed name, not a string prefix
    — a churn-0 quiescent entry is ~10x the churned number and must
    never stand in for it."""
    return ("multidc" if multidc else "gossip",
            None if multidc else churn_ppm, dissem, hot, flight, shard,
            nemesis)


def _parse_metric_regime(name: str) -> tuple | None:
    name = name.rpartition(":")[2]  # strip a non-chip platform prefix
    m = _METRIC_RE.match(name)
    if not m:
        return None
    variant = m.group(1)
    churn = int(m.group(3)) if m.group(3) is not None else 1000
    return (variant, None if variant == "multidc" else churn,
            m.group(6).lstrip("_") if m.group(6) is not None else "swar",
            int(m.group(5)) if m.group(5) is not None else 0,
            m.group(7) is not None,
            int(m.group(8)) if m.group(8) is not None else 0,
            m.group(9) or "")


def _read_cache() -> dict:
    try:
        with open(_LAST_PATH) as f:
            cache = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(cache, dict) or "metric" in cache:
        return {}
    return cache


_PLATFORM = "unknown"  # set by _setup_jax; tags every cached result


_CHIP_PLATFORMS = {"axon", "tpu"}  # one equivalence class: the real chip


def _same_platform_class(a: str, b: str) -> bool:
    return a == b or (a in _CHIP_PLATFORMS and b in _CHIP_PLATFORMS)


def _read_last_good(multidc: bool, churn_ppm: int, dissem: str = "swar",
                    hot: int = 0, flight: bool = False, shard: int = 0,
                    nemesis: str = "",
                    platform: str | None = None) -> dict | None:
    """Last cached measurement of this exact regime (variant + churn +
    strategy) ON THIS BACKEND PLATFORM CLASS, preferring the largest n.
    A CPU smoke run must never stand in for a chip measurement (or vice
    versa); "axon"/"tpu"/untagged are all the chip class.  A corrupt
    cache must never take down the metric emit."""
    want = _regime_key(multidc, churn_ppm, dissem, hot, flight, shard,
                       nemesis)
    plat = platform if platform is not None else _PLATFORM
    candidates = [
        v for k, v in _read_cache().items()
        if isinstance(v, dict) and _parse_metric_regime(k) == want
        and _same_platform_class(v.get("platform", "axon"), plat)]
    if not candidates:
        return None
    return max(candidates, key=lambda v: v.get("n_nodes", 0))


def _store_result(result: dict) -> None:
    """Cache keyed by (platform, metric): a smoke run on another
    backend never displaces the chip's last-known-good."""
    try:
        cache = _read_cache()
        key = (result["metric"] if _PLATFORM in ("axon", "tpu")
               else f"{_PLATFORM}:{result['metric']}")
        cache[key] = {**result, "platform": _PLATFORM,
                      "measured_unix": int(time.time())}
        with open(_LAST_PATH, "w") as f:
            json.dump(cache, f)
    except OSError:
        pass


def _run_regime(jax, args, *, multidc: bool, churn_ppm: int,
                dissem: str = "swar", hot_slots: int = 0,
                flight: bool = False, shard_devices: int = 0,
                nemesis: str = "") -> dict:
    """One regime with reduced-N fallback.  Returns a result dict; on
    total failure returns an error dict carrying the regime-matched
    last-known-good."""
    n = args.n
    last_err: Exception | None = None
    first = True
    # One timeline per regime: probe history lives in _BOOT; this one
    # carries compile/measure/salvage and is attached to the result for
    # successful AND failed regimes (the diagnosable-zero requirement).
    tl = _Timeline()
    while first or n >= MIN_FALLBACK_N:
        first = False
        if shard_devices:
            # Keep the sharded alignment (n divisible by device count
            # and lan_profile's probe_every=5) through the reduced-N
            # fallback ladder.
            n -= n % (shard_devices * 5)
        try:
            if multidc:
                result = _bench_multidc(jax, n, args.dcs, args.slots,
                                        args.steps, args.repeats, tl=tl)
            else:
                result = _bench_lan(jax, n, args.slots, args.steps,
                                    args.repeats, churn_ppm=churn_ppm,
                                    dissem=dissem,
                                    hot_slots=hot_slots, flight=flight,
                                    shard_devices=shard_devices,
                                    nemesis=nemesis, tl=tl)
            if n != args.n:
                result["reduced_from_n"] = args.n
            result["phases"] = tl.events
            _store_result(result)
            return result
        except Exception as e:
            last_err = e
            _log(f"run at n={n} failed: {type(e).__name__}: {e}")
            from_n, n = n, n // 4
            if n >= MIN_FALLBACK_N:
                _log(f"falling back to n={n}")
                tl.note("salvage", outcome="reduced_n", from_n=from_n,
                        to_n=n, error=f"{type(e).__name__}: {e}")
            else:
                tl.note("salvage", outcome="gave_up", from_n=from_n,
                        error=f"{type(e).__name__}: {e}")
    fail_metric = ("swim_multidc_rounds_per_sec" if multidc
                   else "swim_gossip_rounds_per_sec")
    payload = {"metric": fail_metric, "value": 0.0, "unit": "rounds/s",
               "vs_baseline": 0.0,
               "error": f"all sizes failed; last: "
                        f"{type(last_err).__name__}: {last_err}"}
    last = _read_last_good(multidc, churn_ppm, dissem, hot_slots,
                           flight, shard_devices, nemesis)
    if last is not None:
        payload["last_known_good"] = last
        tl.note("salvage", outcome="last_known_good",
                metric=last.get("metric"), value=last.get("value"))
    payload["phases"] = tl.events
    return payload


def _roofline(n: int, slots: int) -> float:
    """Dense-regime ceiling for ANY implementation of these semantics on
    this chip: DENSE_PASSES_PER_ROUND materializations of the S×N belief
    matrix per round at the measured effective HBM rate (shared
    derivation: obs/devstats.py)."""
    return EFFECTIVE_HBM_GBPS * 1e9 / dense_bytes_per_round(slots, n)


# The regime table by name, for `--regime NAME` (diagnosis reruns of
# exactly one table row — the full table costs a chip-hour).  Keys match
# the payload's regimes{} keys; churn1000ppm_shard{d} is accepted via
# the pattern below.
_NAMED_REGIMES: dict[str, dict] = {
    "healthy": dict(multidc=False, churn_ppm=0),
    "healthy_flight": dict(multidc=False, churn_ppm=0, flight=True),
    "healthy_prefused": dict(multidc=False, churn_ppm=0,
                             dissem="prefused"),
    "churn1000ppm": dict(multidc=False, churn_ppm=1000),
    "churn1000ppm_planes": dict(multidc=False, churn_ppm=1000,
                                dissem="planes"),
    "churn1000ppm_prefused": dict(multidc=False, churn_ppm=1000,
                                  dissem="prefused"),
    "realistic_churn10ppm": dict(multidc=False, churn_ppm=10),
    "realistic_churn10ppm_hot8": dict(multidc=False, churn_ppm=10,
                                      hot_slots=8),
    "multidc": dict(multidc=True, churn_ppm=0),
    "nemesis_asym_loss": dict(multidc=False, churn_ppm=1000,
                              nemesis="asym_loss"),
    "nemesis_degraded_observer": dict(multidc=False, churn_ppm=1000,
                                      nemesis="degraded_observer"),
}

_SHARD_REGIME_RE = re.compile(r"^churn1000ppm_shard(\d+)$")


def _named_regime(name: str) -> dict:
    """_run_regime kwargs for a regime-table row name; raises
    SystemExit with the known names on a miss (argparse convention)."""
    if name in _NAMED_REGIMES:
        return dict(_NAMED_REGIMES[name])
    m = _SHARD_REGIME_RE.match(name)
    if m:
        return dict(multidc=False, churn_ppm=1000,
                    shard_devices=int(m.group(1)))
    known = ", ".join(sorted(_NAMED_REGIMES) + ["churn1000ppm_shard{d}"])
    raise SystemExit(f"unknown --regime {name!r}; known: {known}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000, help="simulated cluster size")
    ap.add_argument("--slots", type=int, default=64, help="concurrent rumor slots")
    ap.add_argument("--steps", type=int, default=512, help="rounds per timed block")
    ap.add_argument("--repeats", type=int, default=3, help="timed blocks (best taken)")
    ap.add_argument("--multidc", action="store_true",
                    help="single regime: BASELINE config #5 shape")
    ap.add_argument("--dcs", type=int, default=4, help="datacenters (multidc)")
    ap.add_argument("--churn-ppm", type=int, default=None,
                    help="single regime: failing nodes per million; 0 = "
                         "healthy-cluster (quiescent fast path)")
    ap.add_argument("--dissem",
                    choices=("swar", "planes", "prefused", "fused"),
                    default="swar",
                    help="dissemination strategy for single-regime runs "
                         "(params.dissem; the table A/Bs swar vs planes "
                         "vs prefused)")
    ap.add_argument("--hot-slots", dest="hot_slots", type=int, default=0,
                    help="hot-tier width for single-regime runs "
                         "(the table A/Bs full vs hot8 at realistic churn)")
    ap.add_argument("--flight", action="store_true",
                    help="enable the kernel flight recorder for "
                         "single-regime runs (the table A/Bs the healthy "
                         "regime with and without it)")
    ap.add_argument("--shard-devices", dest="shard_devices", type=int,
                    default=0,
                    help="run the shard_map'd kernel over this many local "
                         "devices for single-regime runs (0 = unsharded; "
                         "the table sweeps 1..all local devices)")
    ap.add_argument("--nemesis", type=str, default="",
                    help="run the timed blocks under this nemesis "
                         "injection schedule (gossip/nemesis.py catalog "
                         "name, window widened to the whole run); the "
                         "table A/Bs two scenarios against churn1000ppm")
    ap.add_argument("--regime", type=str, default="",
                    help="run exactly one regime-table row by its "
                         "payload key (healthy, churn1000ppm_planes, "
                         "churn1000ppm_shard2, ...) — the diagnosis "
                         "rerun path; combines with --n/--steps etc.")
    args = ap.parse_args()

    single_regime = (args.multidc or args.churn_ppm is not None
                     or bool(args.nemesis) or bool(args.regime))

    try:
        jax = _setup_jax()
    except Exception as e:
        # Backend never came up: report the failure honestly, but carry
        # the regime-matched last-known-good evidence for the backend
        # this run WOULD have measured (the round-3 artifact carried
        # only one stale number and the whole regime story was lost).
        plat = "cpu" if _want_cpu() else "axon"
        if args.regime:
            rk = _named_regime(args.regime)
            multidc, churn = rk["multidc"], rk["churn_ppm"]
        elif args.multidc:
            multidc, churn = True, 0
        else:
            churn = args.churn_ppm if args.churn_ppm is not None else 0
            multidc = False
        payload = {"metric": ("swim_multidc_rounds_per_sec" if multidc
                              else "swim_gossip_rounds_per_sec"),
                   "value": 0.0, "unit": "rounds/s", "vs_baseline": 0.0,
                   "error": f"backend init: {e}"}
        if single_regime:
            last = _read_last_good(multidc, churn, platform=plat)
            if last is not None:
                payload["last_known_good"] = last
        else:
            lkg = {
                "healthy": _read_last_good(False, 0, platform=plat),
                "healthy_flight": _read_last_good(False, 0, flight=True,
                                                  platform=plat),
                "churn1000ppm": _read_last_good(False, 1000, platform=plat),
                "churn1000ppm_planes": _read_last_good(
                    False, 1000, dissem="planes", platform=plat),
                "healthy_prefused": _read_last_good(
                    False, 0, dissem="prefused", platform=plat),
                "churn1000ppm_prefused": _read_last_good(
                    False, 1000, dissem="prefused", platform=plat),
                "realistic_churn10ppm": _read_last_good(
                    False, 10, platform=plat),
                "realistic_churn10ppm_hot8": _read_last_good(
                    False, 10, hot=8, platform=plat),
                "multidc": _read_last_good(True, 0, platform=plat),
            }
            payload["regimes_last_known_good"] = {
                k: v for k, v in lkg.items() if v is not None}
            if lkg["churn1000ppm"] is not None:  # the headline regime
                payload["last_known_good"] = lkg["churn1000ppm"]
        payload["boot_phases"] = _BOOT.events
        _emit(payload)
        return

    if single_regime:
        if args.regime:
            kwargs = _named_regime(args.regime)
        else:
            churn = args.churn_ppm if args.churn_ppm is not None else 1000
            kwargs = dict(multidc=args.multidc, churn_ppm=churn,
                          dissem=args.dissem,
                          hot_slots=args.hot_slots, flight=args.flight,
                          shard_devices=args.shard_devices,
                          nemesis=args.nemesis)
        payload = _run_regime(jax, args, **kwargs)
        payload["boot_phases"] = _BOOT.events
        _emit(payload)
        return

    # -- default: the full regime table, one JSON line -------------------
    regimes: dict[str, dict] = {}
    regimes["healthy"] = _run_regime(jax, args, multidc=False, churn_ppm=0)
    # Flight-recorder overhead A/B at the healthy operating point: the
    # acceptance bar is <5% regression with the recorder enabled.
    regimes["healthy_flight"] = _run_regime(jax, args, multidc=False,
                                            churn_ppm=0, flight=True)
    regimes["churn1000ppm"] = _run_regime(jax, args, multidc=False,
                                          churn_ppm=1000)
    # Dissemination-strategy A/Bs in the stress regime: the table
    # records all so the better lowering is picked from evidence
    # (params.dissem), not hope.  prefused is the round-12 one-fewer-
    # HBM-pass variant (age commuted across the rolls); it also rides
    # the healthy regime because the quiescent fast path must not
    # regress from carrying the alternate tail.
    regimes["churn1000ppm_planes"] = _run_regime(
        jax, args, multidc=False, churn_ppm=1000, dissem="planes")
    regimes["churn1000ppm_prefused"] = _run_regime(
        jax, args, multidc=False, churn_ppm=1000, dissem="prefused")
    regimes["healthy_prefused"] = _run_regime(
        jax, args, multidc=False, churn_ppm=0, dissem="prefused")
    # Hot-tier A/B at realistic churn (1-2 live episodes — the regime
    # the tier exists for; bench churn is ~100x real failure rates):
    # the captured pair IS the hot_slots default decision the last two
    # rounds could not make without chip access.
    regimes["realistic_churn10ppm"] = _run_regime(
        jax, args, multidc=False, churn_ppm=10)
    regimes["realistic_churn10ppm_hot8"] = _run_regime(
        jax, args, multidc=False, churn_ppm=10, hot_slots=8)
    regimes["multidc"] = _run_regime(jax, args, multidc=True, churn_ppm=0)
    # Nemesis fault-injection overhead A/Bs (gossip/nemesis.py,
    # BENCH_NOTES §8) against the churn1000ppm baseline: asym_loss
    # prices the partition/loss edge masks, degraded_observer the
    # Lifeguard LHM state threaded through the scan carry.  Each also
    # reports its scenario-attributed detection SLO from the untimed
    # observatory block.
    regimes["nemesis_asym_loss"] = _run_regime(
        jax, args, multidc=False, churn_ppm=1000, nemesis="asym_loss")
    regimes["nemesis_degraded_observer"] = _run_regime(
        jax, args, multidc=False, churn_ppm=1000,
        nemesis="degraded_observer")
    # ICI-sharding scaling curve (BENCH_NOTES §sharding): the
    # shard_map'd kernel at the headline churn regime, one entry per
    # power-of-two local device count.  shard1 isolates the shard_map
    # wrapping + collective-schedule overhead against the plain kernel;
    # the top entry is the paper posture (all chips on the ring).
    d = 1
    while d <= len(jax.devices()):
        regimes[f"churn1000ppm_shard{d}"] = _run_regime(
            jax, args, multidc=False, churn_ppm=1000, shard_devices=d)
        d *= 2

    # The historical churn regime stays the headline so cross-round
    # comparisons (and vs_baseline against the 10k target) remain
    # apples-to-apples; the regimes dict carries the healthy/multidc
    # numbers alongside.
    headline = regimes["churn1000ppm"]
    payload = {
        "metric": headline.get("metric", "swim_gossip_rounds_per_sec"),
        "value": headline.get("value", 0.0),
        "unit": "rounds/s",
        "vs_baseline": headline.get("vs_baseline", 0.0),
        "regimes": regimes,
        "roofline_rounds_per_sec": round(_roofline(args.n, args.slots), 1),
        "roofline_note": (f"{DENSE_PASSES_PER_ROUND} S*N passes/round @ "
                          f"{EFFECTIVE_HBM_GBPS:.0f} GB/s effective; "
                          "healthy regime takes the quiescent fast path "
                          "and is not bounded by it"),
        "measured_live": [k for k, v in regimes.items() if "error" not in v],
        "boot_phases": _BOOT.events,
    }
    if "error" in headline:
        payload["error"] = headline["error"]
        lkg = headline.get("last_known_good")
        if lkg is not None:
            # One wedged regime must not zero the whole round's headline
            # series: substitute the regime-matched last-known-good and
            # mark the provenance so a reader can tell it from a live
            # measurement.
            payload["value"] = lkg.get("value", 0.0)
            payload["vs_baseline"] = lkg.get("vs_baseline", 0.0)
            payload["headline_source"] = "last_known_good"
            payload["last_known_good"] = lkg
    else:
        payload["headline_source"] = "live"
    _emit(payload)


if __name__ == "__main__":
    main()
