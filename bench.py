"""North-star benchmark: SWIM gossip rounds/sec at 1M simulated nodes.

Target from BASELINE.json config #5: >=10k gossip rounds/sec at 1M nodes
(reference substrate: memberlist's event-driven gossip, which the TPU
kernel re-designs as batched synchronous rounds — see
consul_tpu/gossip/kernel.py).  vs_baseline is measured rounds/sec over
that 10k/s target.

Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import argparse
import json
import sys
import time

TARGET_ROUNDS_PER_SEC = 10_000.0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000, help="simulated cluster size")
    ap.add_argument("--slots", type=int, default=64, help="concurrent rumor slots")
    ap.add_argument("--steps", type=int, default=512, help="rounds per timed block")
    ap.add_argument("--repeats", type=int, default=3, help="timed blocks (best taken)")
    ap.add_argument("--multidc", action="store_true",
                    help="BASELINE config #5 shape: LAN+WAN pools + events")
    ap.add_argument("--dcs", type=int, default=4, help="datacenters (multidc)")
    args = ap.parse_args()

    if args.multidc:
        bench_multidc(args)
        return

    import jax
    import jax.numpy as jnp

    from consul_tpu.gossip.kernel import init_state, run_rounds
    from consul_tpu.gossip.params import lan_profile

    p = lan_profile(args.n, slots=args.slots)
    state = init_state(p)
    key = jax.random.PRNGKey(42)
    # Steady-state failure churn: a fixed 0.1% of nodes fail at staggered
    # rounds spanning warmup AND every timed block, so probe/suspect/dead/GC
    # paths stay hot in whichever block min() selects.
    n_fail = max(1, args.n // 1000)
    total_rounds = args.steps * (args.repeats + 1)
    # Stride, not modulo: failures land uniformly across every block even
    # when n_fail < total_rounds.
    fail_round = (
        jnp.full((p.n,), 2**31 - 1, jnp.int32)
        .at[: n_fail]
        .set((jnp.arange(n_fail, dtype=jnp.int32) * total_rounds) // n_fail)
    )

    # Compile + warm up.
    state, _ = run_rounds(state, key, fail_round, p, steps=args.steps)
    jax.block_until_ready(state)

    best = float("inf")
    for _ in range(args.repeats):
        t0 = time.perf_counter()
        state, _ = run_rounds(state, key, fail_round, p, steps=args.steps)
        jax.block_until_ready(state)
        best = min(best, time.perf_counter() - t0)

    rounds_per_sec = args.steps / best
    print(
        json.dumps(
            {
                "metric": f"swim_gossip_rounds_per_sec_{args.n}_nodes",
                "value": round(rounds_per_sec, 1),
                "unit": "rounds/s",
                "vs_baseline": round(rounds_per_sec / TARGET_ROUNDS_PER_SEC, 3),
            }
        )
    )
    sys.stdout.flush()


def bench_multidc(args) -> None:
    """Config #5: D LAN pools + WAN pool + cross-DC event propagation."""
    import jax
    import jax.numpy as jnp

    from consul_tpu.gossip.kernel import NEVER
    from consul_tpu.gossip.multidc import (
        fire_in_dc, init_multidc, make_params, run_multidc_rounds)

    n_lan = args.n // args.dcs
    p = make_params(n_dcs=args.dcs, n_lan=n_lan, n_servers=3,
                    event_slots=32, slots=args.slots)
    state = init_multidc(p)
    state = fire_in_dc(state, dc=0, node=7, p=p)
    key = jax.random.PRNGKey(42)
    n_fail = max(1, n_lan // 1000)
    total_rounds = args.steps * (args.repeats + 1)
    per_dc = (jnp.arange(n_fail, dtype=jnp.int32) * total_rounds) // n_fail
    # Offset past the server ids: killing the bridge nodes would bench a
    # topology with no live LAN<->WAN relay.
    s0 = p.n_servers
    lan_fail = (jnp.full((p.n_dcs, n_lan), NEVER, jnp.int32)
                .at[:, s0:s0 + n_fail].set(per_dc[None, :]))
    wan_fail = jnp.full((p.n_dcs * p.n_servers,), NEVER, jnp.int32)

    state, _ = run_multidc_rounds(state, key, lan_fail, wan_fail, p,
                                  steps=args.steps)
    jax.block_until_ready(state)

    best = float("inf")
    for _ in range(args.repeats):
        t0 = time.perf_counter()
        state, _ = run_multidc_rounds(state, key, lan_fail, wan_fail, p,
                                      steps=args.steps)
        jax.block_until_ready(state)
        best = min(best, time.perf_counter() - t0)

    rounds_per_sec = args.steps / best
    print(json.dumps({
        "metric": f"swim_multidc_rounds_per_sec_{args.n}_nodes_{args.dcs}dc",
        "value": round(rounds_per_sec, 1),
        "unit": "rounds/s",
        "vs_baseline": round(rounds_per_sec / TARGET_ROUNDS_PER_SEC, 3),
    }))
    sys.stdout.flush()


if __name__ == "__main__":
    main()
