"""North-star benchmark: SWIM gossip rounds/sec at 1M simulated nodes.

Target from BASELINE.json config #5: >=10k gossip rounds/sec at 1M nodes
(reference substrate: memberlist's event-driven gossip, which the TPU
kernel re-designs as batched synchronous rounds — see
consul_tpu/gossip/kernel.py).  vs_baseline is measured rounds/sec over
that 10k/s target.

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
All progress/diagnostics go to stderr.  Resilience (round-1 failure was
an unretried backend-init crash with no JSON at all):
  * backend init is retried with backoff;
  * a persistent compilation cache (.jax_cache/) amortizes the 1M-node
    compile across invocations;
  * compile time is measured and reported separately from steady state;
  * if the full-size run fails (init/OOM/compile), the benchmark backs
    off to n/4 repeatedly and reports the largest size that ran;
  * any terminal failure still emits a parseable JSON line with an
    "error" field instead of a bare traceback.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

TARGET_ROUNDS_PER_SEC = 10_000.0
MIN_FALLBACK_N = 65_536


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def _probe_backend(timeout_s: float) -> tuple[bool, str]:
    """Initialize the jax backend in a THROWAWAY subprocess with a hard
    timeout.  Backend init dials the TPU tunnel and can hang
    indefinitely inside a C call (uninterruptible in-process — the
    round-1 failure shape), so the liveness check must be a process we
    can kill."""
    import subprocess

    code = "import jax; d = jax.devices(); print(d[0].platform, len(d))"
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, f"backend init exceeded {timeout_s:.0f}s (tunnel hang?)"
    if r.returncode == 0:
        return True, r.stdout.strip()
    tail = (r.stderr or "").strip().splitlines()
    return False, "; ".join(tail[-3:]) if tail else f"rc={r.returncode}"


def _setup_jax(retries: int = 2, probe_timeout_s: float = 240.0):
    """Probe backend liveness out-of-process, then init in-process with
    the persistent compile cache enabled."""
    last = "unknown"
    for attempt in range(1, retries + 1):
        ok, info = _probe_backend(probe_timeout_s)
        if ok:
            _log(f"backend probe ok: {info}")
            break
        last = info
        _log(f"backend probe failed (attempt {attempt}/{retries}): {info}")
        if attempt < retries:
            time.sleep(15.0 * attempt)
    else:
        raise RuntimeError(f"jax backend unreachable after {retries} probes: {last}")

    import jax

    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # cache flags are best-effort across jax versions
        _log(f"compilation cache unavailable: {e}")

    devs = jax.devices()
    _log(f"backend up: {len(devs)}x {devs[0].platform} "
         f"({getattr(devs[0], 'device_kind', '?')})")
    return jax


def _sync(jax, state) -> None:
    """Wait for the step to FINISH, not merely be enqueued.  On the
    tunneled axon backend block_until_ready can return once the handle
    is committed rather than executed (observed: 2.8M rounds/s, ~1000x
    the HBM roofline — physically impossible); a device->host scalar
    fetch cannot lie about completion."""
    jax.block_until_ready(state)
    int(state.round if hasattr(state, "round") else jax.tree.leaves(state)[0])


def _bench_lan(jax, n: int, slots: int, steps: int, repeats: int,
               churn_ppm: int = 1000) -> dict:
    import jax.numpy as jnp

    from consul_tpu.gossip.kernel import init_state, run_rounds
    from consul_tpu.gossip.params import lan_profile

    p = lan_profile(n, slots=slots)
    state = init_state(p)
    key = jax.random.PRNGKey(42)
    # Steady-state failure churn (default 0.1% of nodes, staggered over
    # warmup AND every timed block, so probe/suspect/dead/GC paths stay
    # hot in whichever block min() selects).  --churn-ppm 0 benches the
    # healthy-cluster regime: no episodes, rounds take the quiescent
    # fast path (probe tick only).
    n_fail = (n * churn_ppm) // 1_000_000 if churn_ppm else 0
    if churn_ppm and n_fail == 0:
        n_fail = 1
    total_rounds = steps * (repeats + 1)
    # Stride, not modulo: failures land uniformly across every block even
    # when n_fail < total_rounds.
    fail_round = jnp.full((p.n,), 2**31 - 1, jnp.int32)
    if n_fail:
        # Stride, not modulo: failures land uniformly across every block.
        fail_round = fail_round.at[:n_fail].set(
            (jnp.arange(n_fail, dtype=jnp.int32) * total_rounds) // n_fail)

    _log(f"lan n={n} slots={slots}: compiling + warmup ({steps} rounds)")
    t0 = time.perf_counter()
    state, _ = run_rounds(state, key, fail_round, p, steps=steps)
    _sync(jax, state)
    compile_s = time.perf_counter() - t0
    _log(f"compile+warmup done in {compile_s:.1f}s")

    best = float("inf")
    for r in range(repeats):
        t0 = time.perf_counter()
        state, _ = run_rounds(state, key, fail_round, p, steps=steps)
        _sync(jax, state)
        dt = time.perf_counter() - t0
        best = min(best, dt)
        _log(f"block {r + 1}/{repeats}: {steps / dt:.1f} rounds/s")

    rps = steps / best
    return {
        "metric": (f"swim_gossip_rounds_per_sec_{n}_nodes"
                   + ("" if churn_ppm == 1000 else f"_churn{churn_ppm}ppm")),
        "value": round(rps, 1),
        "unit": "rounds/s",
        "vs_baseline": round(rps / TARGET_ROUNDS_PER_SEC, 3),
        "compile_s": round(compile_s, 1),
        "n_nodes": n,
    }


def _bench_multidc(jax, n: int, dcs: int, slots: int, steps: int,
                   repeats: int) -> dict:
    """Config #5 shape: D LAN pools + WAN pool + cross-DC event propagation."""
    import jax.numpy as jnp

    from consul_tpu.gossip.kernel import NEVER
    from consul_tpu.gossip.multidc import (
        fire_in_dc, init_multidc, make_params, run_multidc_rounds)

    n_lan = n // dcs
    p = make_params(n_dcs=dcs, n_lan=n_lan, n_servers=3,
                    event_slots=32, slots=slots)
    state = init_multidc(p)
    state = fire_in_dc(state, dc=0, node=7, p=p)
    key = jax.random.PRNGKey(42)
    n_fail = max(1, n_lan // 1000)
    total_rounds = steps * (repeats + 1)
    per_dc = (jnp.arange(n_fail, dtype=jnp.int32) * total_rounds) // n_fail
    # Offset past the server ids: killing the bridge nodes would bench a
    # topology with no live LAN<->WAN relay.
    s0 = p.n_servers
    lan_fail = (jnp.full((p.n_dcs, n_lan), NEVER, jnp.int32)
                .at[:, s0:s0 + n_fail].set(per_dc[None, :]))
    wan_fail = jnp.full((p.n_dcs * p.n_servers,), NEVER, jnp.int32)

    _log(f"multidc n={n} dcs={dcs}: compiling + warmup ({steps} rounds)")
    t0 = time.perf_counter()
    state, _ = run_multidc_rounds(state, key, lan_fail, wan_fail, p,
                                  steps=steps)
    _sync(jax, state.wan)
    compile_s = time.perf_counter() - t0
    _log(f"compile+warmup done in {compile_s:.1f}s")

    best = float("inf")
    for r in range(repeats):
        t0 = time.perf_counter()
        state, _ = run_multidc_rounds(state, key, lan_fail, wan_fail, p,
                                      steps=steps)
        _sync(jax, state.wan)
        dt = time.perf_counter() - t0
        best = min(best, dt)
        _log(f"block {r + 1}/{repeats}: {steps / dt:.1f} rounds/s")

    rps = steps / best
    return {
        "metric": f"swim_multidc_rounds_per_sec_{n}_nodes_{dcs}dc",
        "value": round(rps, 1),
        "unit": "rounds/s",
        "vs_baseline": round(rps / TARGET_ROUNDS_PER_SEC, 3),
        "compile_s": round(compile_s, 1),
        "n_nodes": n,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000, help="simulated cluster size")
    ap.add_argument("--slots", type=int, default=64, help="concurrent rumor slots")
    ap.add_argument("--steps", type=int, default=512, help="rounds per timed block")
    ap.add_argument("--repeats", type=int, default=3, help="timed blocks (best taken)")
    ap.add_argument("--multidc", action="store_true",
                    help="BASELINE config #5 shape: LAN+WAN pools + events")
    ap.add_argument("--dcs", type=int, default=4, help="datacenters (multidc)")
    ap.add_argument("--churn-ppm", type=int, default=1000,
                    help="failing nodes per million over the run; 0 = "
                         "healthy-cluster regime (quiescent fast path)")
    args = ap.parse_args()

    fail_metric = ("swim_multidc_rounds_per_sec" if args.multidc
                   else "swim_gossip_rounds_per_sec")
    last_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".bench_last_success.json")

    def _read_last_good() -> dict | None:
        """Cached measurements, keyed by full metric name (bench variant
        + size) so a small-n smoke run never displaces the headline 1M
        number.  Lookup prefers the largest n among entries of this
        variant.  A corrupt cache must never take down the metric emit."""
        try:
            with open(last_path) as f:
                cache = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(cache, dict):
            return None
        candidates = [v for k, v in cache.items()
                      if k.startswith(fail_metric) and isinstance(v, dict)]
        # pre-keying format: a single flat result dict
        if not candidates and str(cache.get("metric", "")).startswith(fail_metric):
            candidates = [cache]
        if not candidates:
            return None
        return max(candidates, key=lambda v: v.get("n_nodes", 0))

    def _emit_failure(err: str) -> None:
        # The tunnel to the chip wedges occasionally (grant held by a
        # killed process).  Report the failure honestly, but attach the
        # last successfully measured value so a flaky tunnel at
        # round-end doesn't erase a real measurement.
        payload = {"metric": fail_metric, "value": 0.0,
                   "unit": "rounds/s", "vs_baseline": 0.0, "error": err}
        last = _read_last_good()
        if last is not None:
            payload["last_known_good"] = last
        _emit(payload)

    try:
        jax = _setup_jax()
    except Exception as e:
        _emit_failure(f"backend init: {e}")
        return

    n = args.n
    last_err: Exception | None = None
    while True:
        try:
            if args.multidc:
                result = _bench_multidc(jax, n, args.dcs, args.slots,
                                        args.steps, args.repeats)
            else:
                result = _bench_lan(jax, n, args.slots, args.steps,
                                    args.repeats, churn_ppm=args.churn_ppm)
            if n != args.n:
                result["reduced_from_n"] = args.n
            try:
                try:
                    with open(last_path) as f:
                        cache = json.load(f)
                    if not isinstance(cache, dict) or "metric" in cache:
                        cache = {}
                except (OSError, ValueError):
                    cache = {}
                cache[result["metric"]] = {**result,
                                           "measured_unix": int(time.time())}
                with open(last_path, "w") as f:
                    json.dump(cache, f)
            except OSError:
                pass
            _emit(result)
            return
        except Exception as e:
            last_err = e
            _log(f"run at n={n} failed: {type(e).__name__}: {e}")
            n //= 4
            if n < MIN_FALLBACK_N:
                break
            _log(f"falling back to n={n}")

    _emit_failure(f"all sizes failed; last: {type(last_err).__name__}: {last_err}")


if __name__ == "__main__":
    main()
