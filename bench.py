"""North-star benchmark: SWIM gossip rounds/sec at 1M simulated nodes.

Target from BASELINE.json config #5: >=10k gossip rounds/sec at 1M nodes
(reference substrate: memberlist's event-driven gossip, which the TPU
kernel re-designs as batched synchronous rounds — see
consul_tpu/gossip/kernel.py).  vs_baseline is measured rounds/sec over
that 10k/s target.

Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import argparse
import json
import sys
import time

TARGET_ROUNDS_PER_SEC = 10_000.0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000, help="simulated cluster size")
    ap.add_argument("--slots", type=int, default=64, help="concurrent rumor slots")
    ap.add_argument("--steps", type=int, default=512, help="rounds per timed block")
    ap.add_argument("--repeats", type=int, default=3, help="timed blocks (best taken)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from consul_tpu.gossip.kernel import init_state, run_rounds
    from consul_tpu.gossip.params import lan_profile

    p = lan_profile(args.n, slots=args.slots)
    state = init_state(p)
    key = jax.random.PRNGKey(42)
    # Steady-state failure churn: a fixed 0.1% of nodes fail at staggered
    # rounds spanning warmup AND every timed block, so probe/suspect/dead/GC
    # paths stay hot in whichever block min() selects.
    n_fail = max(1, args.n // 1000)
    total_rounds = args.steps * (args.repeats + 1)
    # Stride, not modulo: failures land uniformly across every block even
    # when n_fail < total_rounds.
    fail_round = (
        jnp.full((p.n,), 2**31 - 1, jnp.int32)
        .at[: n_fail]
        .set((jnp.arange(n_fail, dtype=jnp.int32) * total_rounds) // n_fail)
    )

    # Compile + warm up.
    state, _ = run_rounds(state, key, fail_round, p, steps=args.steps)
    jax.block_until_ready(state)

    best = float("inf")
    for _ in range(args.repeats):
        t0 = time.perf_counter()
        state, _ = run_rounds(state, key, fail_round, p, steps=args.steps)
        jax.block_until_ready(state)
        best = min(best, time.perf_counter() - t0)

    rounds_per_sec = args.steps / best
    print(
        json.dumps(
            {
                "metric": f"swim_gossip_rounds_per_sec_{args.n}_nodes",
                "value": round(rounds_per_sec, 1),
                "unit": "rounds/s",
                "vs_baseline": round(rounds_per_sec / TARGET_ROUNDS_PER_SEC, 3),
            }
        )
    )
    sys.stdout.flush()


if __name__ == "__main__":
    main()
